"""Deterministic fault-injection harness (resilience layer, ISSUE 5).

MG-WFBP is synchronous data-parallel SGD: every merge-group collective is a
barrier, so the interesting failure modes — a non-finite gradient, a wedged
dispatch, a preempted host, a chip that never grants — are all *rare* in CI
and *routine* in production. This module makes each of them a first-class,
reproducible test input: a fault plan names exactly which fault fires at
which optimizer step (or phase), so every handling path (skip-step guard,
watchdog escalation, graceful preemption drain, bench chip-unavailable
skip) runs in tier-1 on the CPU mesh instead of being dead code until the
first real outage.

Plan grammar (``MGWFBP_FAULT_PLAN``)::

    plan  := spec (';' spec)*
    spec  := kind ('@' kv (',' kv)*)?
    kind  := 'nan' | 'stall' | 'preempt' | 'chip_unavailable'
    kv    := key '=' value

    nan@step=N[,count=C]        poison the batch of optimizer steps
                                N..N+C-1 (1-indexed, host iteration
                                counter) with NaN inputs -> non-finite
                                gradients after the allreduce
    stall@secs=S[,phase=P][,step=N]
                                sleep S seconds inside phase P ('train'
                                default, or 'eval'); with step=N only at
                                that step; fires ONCE
    preempt@step=N[,signal=SIGTERM|SIGINT]
                                deliver the preemption signal after step N
                                completes (the graceful-drain path); ONCE
    chip_unavailable            backend init reports the chip as
                                unavailable (bench.py's ChipUnavailable
                                structured-skip path)
    kill@step=N                 SIGKILL self after step N completes — a
                                HARD crash, no drain, no checkpoint
                                barrier (the supervisor's healer is what
                                recovers the group); ONCE
    wedge@step=N,secs=S         stop stepping for S seconds at step N
                                (signal-interruptible sleep, /healthz
                                and /status keep serving) — the
                                liveness monitor's frozen-step signature
                                without killing anything; ONCE

Every kind additionally takes ``proc=I``: the spec fires only on the
process with that index (multi-host runs share one MGWFBP_FAULT_PLAN env
across the group; ``preempt@step=4,proc=1`` preempts exactly one host so
the agreed group drain is what gets exercised). The trainer applies the
filter via ``FaultPlan.for_process``; a plan without ``proc=`` fires on
every process, exactly as before.

The HARD kinds (kill/wedge) additionally take ``inc=K`` (default 0): the
spec fires only in supervisor incarnation K. Kill and wedge are
drain-less, so the healed relaunch resumes BELOW the fault step — the
crossing semantics below would re-fire the same fault in every life and
the run could never complete. The supervisor exports
``MGWFBP_INCARNATION`` per (re)launch and the trainer applies
``FaultPlan.for_incarnation``, so ``kill@step=4,proc=1`` fires exactly
once, in the first life.

Everything is keyed on deterministic host counters — no randomness — so a
faulted run is exactly reproducible, and a resumed run whose iteration
counter is already past a fault's step does not re-fire it.

Injection stays OUTSIDE the jitted step: NaNs enter through the host batch
(poisoning the inputs makes every post-allreduce gradient non-finite
without recompiling anything), stalls/preemptions are host-side events.
The hot path of an unfaulted run pays one truthiness check per step.
"""

from __future__ import annotations

import dataclasses
import os
import signal
from typing import Optional

ENV_VAR = "MGWFBP_FAULT_PLAN"

# Exit code after a graceful preemption drain: EX_TEMPFAIL, the
# conventional "transient — try again" status, so supervisors (and the
# fault-injection smoke in tools/check.sh) can tell "restart me, progress
# is checkpointed" from a real failure.
PREEMPT_RC = 75


class Preempted(RuntimeError):
    """A preemption signal (SIGTERM/SIGINT) was drained gracefully: the
    in-flight step finished, a step-indexed checkpoint was written, the
    `preempt` telemetry event is in the stream. The launcher converts
    this into exit code PREEMPT_RC."""

    def __init__(self, signal_name: str, epoch: int, iteration: int):
        super().__init__(
            f"preempted by {signal_name} at epoch {epoch} iteration "
            f"{iteration}; progress checkpointed — restart to resume"
        )
        self.signal_name = signal_name
        self.epoch = epoch
        self.iteration = iteration

KINDS = ("nan", "stall", "preempt", "chip_unavailable", "kill", "wedge")
_ALLOWED_KEYS = {
    "nan": {"step", "count", "proc"},
    "stall": {"secs", "phase", "step", "proc"},
    "preempt": {"step", "signal", "proc"},
    "chip_unavailable": {"proc"},
    "kill": {"step", "proc", "inc"},
    "wedge": {"step", "secs", "proc", "inc"},
}
_REQUIRED_KEYS = {
    "nan": {"step"},
    "stall": {"secs"},
    "preempt": {"step"},
    "chip_unavailable": set(),
    "kill": {"step"},
    "wedge": {"step", "secs"},
}
_SIGNALS = {"SIGTERM": signal.SIGTERM, "SIGINT": signal.SIGINT}
# the phases the trainer actually queries; an unknown phase would parse
# and then silently never fire — the no-op the grammar check exists to stop
_PHASES = ("train", "eval")

GRAMMAR = (
    "expected 'kind@key=val,...' specs joined by ';' with kind in "
    f"{KINDS} — e.g. 'nan@step=3;preempt@step=6' (see utils/faults.py)"
)


@dataclasses.dataclass
class FaultSpec:
    kind: str
    step: Optional[int] = None
    count: int = 1
    secs: float = 0.0
    phase: str = "train"
    signal: str = "SIGTERM"
    proc: Optional[int] = None  # None = fire on every process
    inc: int = 0  # kill/wedge: supervisor incarnation the spec fires in
    fired: bool = False  # one-shot kinds (stall/preempt) consume themselves
    fired_steps: set = dataclasses.field(default_factory=set)  # nan kind
    observed_below: bool = False  # preempt: a step < `step` was seen, so
    # reaching `step` is a live crossing, not a resumed counter landing
    # past a fault that already fired in the previous process

    def describe(self) -> str:
        kv = []
        if self.step is not None:
            kv.append(f"step={self.step}")
        if self.kind == "nan" and self.count != 1:
            kv.append(f"count={self.count}")
        if self.kind == "stall":
            kv.append(f"secs={self.secs:g}")
            kv.append(f"phase={self.phase}")
        if self.kind == "preempt":
            kv.append(f"signal={self.signal}")
        if self.kind == "wedge":
            kv.append(f"secs={self.secs:g}")
        if self.proc is not None:
            kv.append(f"proc={self.proc}")
        if self.kind in ("kill", "wedge") and self.inc:
            kv.append(f"inc={self.inc}")
        return self.kind + ("@" + ",".join(kv) if kv else "")


def parse_plan(text: str) -> "FaultPlan":
    """Parse a plan string; malformed input raises ValueError naming the
    offending spec and the grammar (a typo'd fault plan silently injecting
    nothing would defeat the whole point of deterministic injection)."""
    specs: list[FaultSpec] = []
    for raw in text.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        kind, _, argstr = raw.partition("@")
        kind = kind.strip()
        if kind not in KINDS:
            raise ValueError(
                f"fault plan: unknown kind {kind!r} in {raw!r}; {GRAMMAR}"
            )
        kv: dict[str, str] = {}
        if argstr:
            for item in argstr.split(","):
                key, sep, val = item.partition("=")
                key, val = key.strip(), val.strip()
                if not sep or not key or not val:
                    raise ValueError(
                        f"fault plan: malformed arg {item!r} in {raw!r}; "
                        f"{GRAMMAR}"
                    )
                if key not in _ALLOWED_KEYS[kind]:
                    raise ValueError(
                        f"fault plan: {kind!r} takes keys "
                        f"{sorted(_ALLOWED_KEYS[kind])}, got {key!r}"
                    )
                kv[key] = val
        missing = _REQUIRED_KEYS[kind] - kv.keys()
        if missing:
            raise ValueError(
                f"fault plan: {raw!r} missing required key(s) "
                f"{sorted(missing)}; {GRAMMAR}"
            )
        spec = FaultSpec(kind=kind)
        try:
            if "step" in kv:
                spec.step = int(kv["step"])
            if "count" in kv:
                spec.count = int(kv["count"])
            if "secs" in kv:
                spec.secs = float(kv["secs"])
            if "proc" in kv:
                spec.proc = int(kv["proc"])
            if "inc" in kv:
                spec.inc = int(kv["inc"])
        except ValueError:
            raise ValueError(
                f"fault plan: non-numeric value in {raw!r}; {GRAMMAR}"
            ) from None
        if spec.proc is not None and spec.proc < 0:
            raise ValueError("fault plan: proc must be >= 0")
        if spec.inc < 0:
            raise ValueError("fault plan: inc must be >= 0")
        if "phase" in kv:
            if kv["phase"] not in _PHASES:
                raise ValueError(
                    f"fault plan: phase must be one of {list(_PHASES)}, "
                    f"got {kv['phase']!r}"
                )
            spec.phase = kv["phase"]
        if "signal" in kv:
            sig = kv["signal"].upper()
            if sig not in _SIGNALS:
                raise ValueError(
                    f"fault plan: signal must be one of "
                    f"{sorted(_SIGNALS)}, got {kv['signal']!r}"
                )
            spec.signal = sig
        if spec.kind == "nan" and spec.count < 1:
            raise ValueError("fault plan: nan count must be >= 1")
        if spec.kind == "stall" and spec.secs < 0:
            raise ValueError("fault plan: stall secs must be >= 0")
        if spec.kind == "wedge" and spec.secs < 0:
            raise ValueError("fault plan: wedge secs must be >= 0")
        specs.append(spec)
    return FaultPlan(specs)


class FaultPlan:
    """Parsed fault plan; the trainer/bench query it at phase boundaries."""

    def __init__(self, specs: Optional[list[FaultSpec]] = None):
        self.specs = list(specs or [])

    @classmethod
    def from_env(cls, environ=None) -> "FaultPlan":
        text = (environ or os.environ).get(ENV_VAR, "")
        if not text.strip():
            return cls([])
        return parse_plan(text)

    def __bool__(self) -> bool:
        return bool(self.specs)

    def describe(self) -> str:
        return "; ".join(s.describe() for s in self.specs)

    def for_process(self, process_index: int) -> "FaultPlan":
        """The subset of this plan addressed to `process_index`: specs
        with a matching ``proc=`` plus the unaddressed ones. Multi-host
        groups share one MGWFBP_FAULT_PLAN env; this is how each process
        keeps only its own faults."""
        return FaultPlan([
            s for s in self.specs
            if s.proc is None or s.proc == int(process_index)
        ])

    def for_incarnation(self, incarnation: int) -> "FaultPlan":
        """Drop HARD specs (kill/wedge) addressed to a different
        supervisor incarnation. Kill/wedge are drain-less: the healed
        relaunch resumes BELOW the fault step, so without this filter
        the crossing semantics would re-fire the fault in every life
        and the chaos run could never complete. Soft kinds pass through
        unfiltered — their one-shot/crossing semantics already handle
        resumption."""
        return FaultPlan([
            s for s in self.specs
            if s.kind not in ("kill", "wedge")
            or s.inc == int(incarnation)
        ])

    # -- queries (all deterministic in the host counters) -----------------
    def nan_at(self, step: int) -> bool:
        """True when optimizer step `step` (1-indexed) must see NaN grads.

        Each planned step fires ONCE — the fault models a transient flip
        (bad DMA, cosmic ray), so a rollback-and-replay of the same step
        sees clean data; otherwise a deterministic plan would re-poison
        every replay and rollback could never converge."""
        for s in self.specs:
            if (
                s.kind == "nan"
                and s.step <= step < s.step + s.count
                and step not in s.fired_steps
            ):
                s.fired_steps.add(step)
                return True
        return False

    def stall_secs(self, phase: str, step: Optional[int] = None) -> float:
        """Seconds to stall in `phase` at `step` (0.0 = no stall). One-shot:
        a matching spec is consumed so the stall fires exactly once. A
        spec with a step= constraint fires ONLY when the caller reports
        exactly that step — never "on the first call", which would move
        the injected wedge to a different point than the plan names."""
        for s in self.specs:
            if s.kind != "stall" or s.fired or s.phase != phase:
                continue
            if s.step is not None and s.step != step:
                continue
            s.fired = True
            return s.secs
        return 0.0

    def preempt_signal_after(self, step: int) -> Optional[int]:
        """Signal number to deliver after step `step` completed, or None.
        One-shot, and fires only on a live CROSSING of the planned step:
        landing exactly on `step`, or reaching it after a smaller step was
        observed in THIS process. A resumed run whose counter is already
        past `step` consumes the spec silently — the fault fired in the
        previous life, and re-delivering it would preempt every restart
        forever when a supervisor re-runs the same command (same env, same
        plan) on rc PREEMPT_RC."""
        for s in self.specs:
            if s.kind != "preempt" or s.fired:
                continue
            if step < s.step:
                s.observed_below = True
                continue
            s.fired = True
            if s.observed_below or step == s.step:
                return _SIGNALS[s.signal]
        return None

    def chip_unavailable(self) -> bool:
        return any(s.kind == "chip_unavailable" for s in self.specs)

    def kill_after(self, step: int) -> bool:
        """True when the process must SIGKILL ITSELF after step `step`
        completed (drain-less hard crash). Same live-crossing semantics
        as preempt_signal_after — a resumed counter already past the
        planned step consumes the spec silently (belt-and-braces under
        the ``inc=`` filter)."""
        for s in self.specs:
            if s.kind != "kill" or s.fired:
                continue
            if step < s.step:
                s.observed_below = True
                continue
            s.fired = True
            if s.observed_below or step == s.step:
                return True
        return False

    def wedge_secs(self, step: int) -> float:
        """Seconds to stop stepping at exactly step `step` (0.0 = none).
        One-shot, exact-step only — a wedge is a liveness-signature
        fault and must freeze the step counter at precisely the planned
        point, never "on the first call after resume"."""
        for s in self.specs:
            if s.kind != "wedge" or s.fired:
                continue
            if s.step != step:
                continue
            s.fired = True
            return s.secs
        return 0.0
