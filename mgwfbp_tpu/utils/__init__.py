from mgwfbp_tpu.utils.logging import get_logger, run_tag

__all__ = ["get_logger", "run_tag"]
