"""Scalar summary writer — the reference's TensorBoard seam, made live.

The reference scaffolds tensorboardX (`SummaryWriter` construction and
`writer.add_scalar` hooks at dist_trainer.py:19,136-137 and
dl_trainer.py:713-715,753-755) but ships it disabled (`writer = None`). Here
the same seam is a working component: scalars stream to an append-only JSONL
event file next to the run's logs (greppable, no heavyweight dependency), and
when a TensorBoard writer package happens to be installed the same calls
mirror into real event files. The JSONL schema is one object per line:

    {"wall": <unix s>, "step": <int>, "tag": "train/loss", "value": <float>}
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional


class ScalarWriter:
    """Append-only JSONL scalar event writer with optional TensorBoard
    mirroring (tensorboardX or torch.utils.tensorboard, whichever imports;
    neither is required).

    With `stream` (a telemetry `EventWriter`, telemetry/events.py), this
    becomes a thin VIEW over the run's structured event stream: scalars
    are emitted as typed ``scalar`` records into the same file the step
    spans and overlap snapshots land in (one file per run), and no
    separate events.jsonl is opened. Without it, the legacy standalone
    JSONL layout is preserved (schema v1 of the telemetry stream —
    `telemetry.read_events` migrates it)."""

    def __init__(
        self, logdir: str, filename: str = "events.jsonl", stream=None
    ):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        self._stream = stream
        self._f = None
        if stream is None:
            self.path = os.path.join(logdir, filename)
            self._f = open(self.path, "a", buffering=1)  # line-buffered
        else:
            self.path = stream.path
        self._tb = self._make_tb_writer(logdir)

    @staticmethod
    def _make_tb_writer(logdir: str):
        for mod, cls in (
            ("tensorboardX", "SummaryWriter"),
            ("torch.utils.tensorboard", "SummaryWriter"),
        ):
            try:
                import importlib

                m = importlib.import_module(mod)
                return getattr(m, cls)(logdir)
            except Exception:  # noqa: BLE001 — optional dependency probing
                continue
        return None

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        if self._stream is not None:
            try:
                self._stream.emit(
                    "scalar", tag=tag, value=float(value), step=int(step)
                )
            except (TypeError, ValueError):
                raise  # schema misuse is a bug; surface it
            except Exception:  # noqa: BLE001 — a dying stream (disk full)
                # must not take down the training run; same contract as
                # Trainer._emit_event, which disables its end separately
                self._stream = None
        elif self._f is not None:
            self._f.write(
                json.dumps(
                    {
                        "wall": round(time.time(), 3),
                        "step": int(step),
                        "tag": tag,
                        "value": float(value),
                    }
                )
                + "\n"
            )
        if self._tb is not None:
            self._tb.add_scalar(tag, float(value), int(step))

    def add_scalars(self, prefix: str, scalars: dict, step: int) -> None:
        for k, v in scalars.items():
            try:
                self.add_scalar(f"{prefix}/{k}", float(v), step)
            except (TypeError, ValueError):
                continue  # non-scalar metric (e.g. nested dict)

    def close(self) -> None:
        # a shared stream is owned by its creator (the trainer), not here
        if self._f is not None and not self._f.closed:
            self._f.close()
        if self._tb is not None:
            try:
                self._tb.close()
            except Exception:  # noqa: BLE001
                pass


def read_events(path: str) -> list[dict]:
    """Load an events.jsonl file back (for tests / offline plotting)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
