"""Merge a multi-host group's per-process telemetry streams into ONE
global timeline, plus a per-process overlap/straggler table.

Each process of a `MGWFBP_NUM_PROCESSES>1` run writes its own stream
(``telemetry.pN.jsonl``, process_index in the header's run metadata —
telemetry/events.py `stream_filename`). Post-mortems need the GROUP
view: which host straggled, whether the agreed drain / resume really
covered every process, where the overlap efficiency diverged. This tool
reconstructs that view:

  * every record gets an absolute timestamp ``t`` — span records
    (``start_s`` relative to the stream's header wall anchor) re-anchor
    onto the header wall, everything else keeps its own emit wall — and
    a ``process`` tag; records from every stream merge time-sorted into
    one monotonic timeline. A supervisor-resubmitted run APPENDS to the
    same streams with the original anchor preserved (events.EventWriter),
    so both incarnations land on one continuous axis.
  * the straggler table compares per-process step spans at the same
    global step: a process whose spans consistently exceed the group
    minimum is the straggler the MG-WFBP schedule is stalling on.

Usage:
    python tools/telemetry_merge.py <run-dir>            # report
    python tools/telemetry_merge.py <run-dir> --out merged.jsonl
    python tools/telemetry_merge.py logs/a/telemetry.p0.jsonl \
        logs/a/telemetry.p1.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

from mgwfbp_tpu.telemetry import (  # noqa: E402
    events_of, find_stream_paths, read_event_set,
)


def load_stream(path: str) -> tuple[dict, list[dict]]:
    """(header, records) of one per-process stream (rotation-aware)."""
    records = read_event_set(path)
    if not records or records[0].get("event") != "header":
        raise ValueError(f"{path}: not a telemetry stream (no header)")
    return records[0], records


# default slack for wall-clock steps (NTP) and for a span's re-anchored
# start trailing its emit wall; beyond this the stream is treated as
# corrupt. Long runs that crossed a real clock step (NTP slew, VM
# suspend/resume) can raise it via --clock-slack / slack_s.
_CLOCK_SLACK_S = 1.0


def _validate_stream(
    path: str, anchor: float, records: list[dict],
    slack_s: float = _CLOCK_SLACK_S,
) -> None:
    """The per-stream consistency the global timeline rests on, checked
    BEFORE the merge sort can paper over it: emit walls never go
    backwards across appends/rotation (a resubmitted incarnation extends
    the stream in real time), and every span's re-anchored absolute
    start precedes its own emit wall (a span that 'starts' after it was
    written means the writer lost the set's original anchor)."""
    last_wall = None
    for i, rec in enumerate(records):
        wall = float(rec.get("wall", anchor))
        if last_wall is not None and wall < last_wall - slack_s:
            raise ValueError(
                f"{path}: record {i} wall clock jumps backwards "
                f"({last_wall:.3f} -> {wall:.3f}); segments mis-ordered "
                "or stream corrupt"
            )
        last_wall = wall
        if "start_s" in rec:
            t = anchor + float(rec["start_s"])
            if t > wall + slack_s:
                raise ValueError(
                    f"{path}: record {i} span starts {t - wall:.3f}s "
                    "after its own emit wall — the writer re-anchored "
                    "mid-run and the incarnations no longer share one "
                    "time axis"
                )


def merge_streams(
    paths: list[str], *, slack_s: float = _CLOCK_SLACK_S,
) -> list[dict]:
    """One time-sorted global record list; each record carries ``t``
    (absolute seconds) and ``process`` (stream's process_index). Raises
    ValueError when any input stream is internally inconsistent
    (`_validate_stream`) — a sorted output is only meaningful if the
    per-stream timelines were sane going in."""
    if not paths:
        raise ValueError("no telemetry streams to merge")
    merged: list[dict] = []
    for path in paths:
        header, records = load_stream(path)
        anchor = float(header.get("wall", 0.0))
        run = header.get("run") or {}
        proc = int(run.get("process_index", 0))
        _validate_stream(path, anchor, records, slack_s)
        for rec in records:
            if "start_s" in rec:
                t = anchor + float(rec["start_s"])
            else:
                t = float(rec.get("wall", anchor))
            merged.append({**rec, "process": proc, "t": round(t, 6)})
    merged.sort(key=lambda r: (r["t"], r.get("process", 0)))
    return merged


def straggler_table(merged: list[dict]) -> list[dict]:
    """Per-process step/overlap summary over the merged timeline.

    ``mean_excess_s`` is the per-step span minus the fastest process's
    span at the SAME global step, averaged — the group-synchronous cost
    this process adds. Steps seen by only one process (single-host
    segments) contribute zero excess.
    """
    by_step: dict[tuple, dict[int, float]] = {}
    per_proc: dict[int, dict] = {}
    for rec in events_of(merged, "step"):
        p = int(rec["process"])
        d = per_proc.setdefault(
            p, {"steps": 0, "dur_sum": 0.0, "dur_max": 0.0,
                "excess_sum": 0.0, "efficiency": None},
        )
        d["steps"] += 1
        dur = float(rec["dur_s"])
        d["dur_sum"] += dur
        d["dur_max"] = max(d["dur_max"], dur)
        by_step.setdefault(int(rec["step"]), {})[p] = dur
    for durs in by_step.values():
        if len(durs) < 2:
            continue
        fastest = min(durs.values())
        for p, dur in durs.items():
            per_proc[p]["excess_sum"] += dur - fastest
    for rec in events_of(merged, "overlap"):
        p = int(rec["process"])
        if p in per_proc:
            per_proc[p]["efficiency"] = float(rec["efficiency"])
    rows = []
    for p in sorted(per_proc):
        d = per_proc[p]
        n = max(d["steps"], 1)
        rows.append({
            "process": p,
            "steps": d["steps"],
            "mean_step_s": d["dur_sum"] / n,
            "max_step_s": d["dur_max"],
            "mean_excess_s": d["excess_sum"] / n,
            "overlap_efficiency": d["efficiency"],
        })
    return rows


def check_monotonic(merged: list[dict]) -> None:
    """Output-format guarantee of `merge_streams` (which also validated
    each INPUT stream's internal consistency — the non-trivial half)."""
    last = None
    for rec in merged:
        if last is not None and rec["t"] < last:
            raise AssertionError(
                f"merged timeline not monotonic at t={rec['t']}"
            )
        last = rec["t"]


def render_report(merged: list[dict], paths: list[str]) -> str:
    lines = []
    t0, t1 = merged[0]["t"], merged[-1]["t"]
    procs = sorted({r["process"] for r in merged})
    resumes = events_of(merged, "resume")
    preempts = events_of(merged, "preempt")
    lines.append(
        f"merged {len(merged)} records from {len(paths)} stream(s), "
        f"{len(procs)} process(es), span {t1 - t0:.1f}s"
    )
    if preempts or resumes:
        # every process emits its own preempt/resume rows; incarnations
        # are a GROUP property, so count one process's restarts
        per_proc = max(
            (sum(1 for r in resumes if r["process"] == p) for p in procs),
            default=0,
        )
        lines.append(
            f"lifecycle: {len(preempts)} preempt row(s), {len(resumes)} "
            f"resume row(s) — {per_proc + 1} incarnation(s) on one "
            "timeline"
        )
    rows = straggler_table(merged)
    if rows:
        lines.append("")
        lines.append(
            f"{'proc':>4}  {'steps':>6}  {'mean step':>10}  "
            f"{'max step':>10}  {'straggle':>10}  {'overlap eff':>11}"
        )
        for r in rows:
            eff = (
                f"{r['overlap_efficiency']:.3f}"
                if r["overlap_efficiency"] is not None else "-"
            )
            lines.append(
                f"{r['process']:>4}  {r['steps']:>6}  "
                f"{r['mean_step_s']:>10.4g}  {r['max_step_s']:>10.4g}  "
                f"{r['mean_excess_s']:>10.4g}  {eff:>11}"
            )
    return "\n".join(lines)


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="+",
                    help="stream files, or one directory holding "
                         "telemetry[.pN].jsonl streams")
    ap.add_argument("--clock-slack", type=float, default=_CLOCK_SLACK_S,
                    metavar="SECONDS",
                    help="wall-clock tolerance for the stream-consistency "
                         "checks (default %(default)ss); raise for runs "
                         "that crossed an NTP step or VM suspend")
    ap.add_argument("--out", default=None,
                    help="write the merged timeline as JSONL here "
                         "(report still prints)")
    args = ap.parse_args(argv)
    paths = list(args.paths)
    if len(paths) == 1 and os.path.isdir(paths[0]):
        paths = find_stream_paths(paths[0])
        if not paths:
            print(f"no telemetry streams under {args.paths[0]}",
                  file=sys.stderr)
            return 2
    merged = merge_streams(paths, slack_s=args.clock_slack)
    check_monotonic(merged)
    if args.out:
        with open(args.out, "w") as f:
            for rec in merged:
                f.write(json.dumps(rec) + "\n")
    print(render_report(merged, paths))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
