"""Greedy-decode WER with TRAIN-mode batch statistics (memorization check).

The DeepSpeech model here uses SequenceWise BatchNorm over (B*T, H)
(reference lstm_models.py:21-42) — on the 45-utterance salvage the
per-batch statistics vary so strongly with the padded-duration mix that
the running averages match NO batch: run 2's TRAIN-mode CTC loss reaches
0.09 while the SAME data in eval mode (running stats) pins at ~37. The
memorization mechanism check (VERDICT r4 #4) is about the
spectrogram -> CTC -> decode -> WER path, so this tool decodes each
train batch under the statistics the model was trained with (train=True
forward, mutable batch_stats update discarded; the model has no dropout,
so the forward is deterministic). At full-AN4 scale (948 utterances) the
running averages converge and the ordinary eval path applies — the gap
is a small-corpus artifact, not a model bug.

Usage:
  XLA_FLAGS=--xla_force_host_platform_device_count=1 JAX_PLATFORMS=cpu \
    python tools/an4_trainmode_wer.py --checkpoint-dir checkpoints/... \
      [--epoch N] [--data-dir data/an4_memcheck]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--checkpoint-dir", required=True)
    ap.add_argument("--data-dir", default="data/an4_memcheck")
    ap.add_argument("--epoch", type=int, default=None)
    args = ap.parse_args(argv)

    from mgwfbp_tpu.utils.platform import apply_platform_overrides

    apply_platform_overrides()

    import tempfile

    import numpy as np

    from mgwfbp_tpu.checkpoint import Checkpointer
    from mgwfbp_tpu.config import make_config
    from mgwfbp_tpu.train.trainer import Trainer

    # make_config drops None overrides, so an explicit throwaway dir is
    # required — otherwise this tool appends its init lines to the
    # default preset's committed run log
    cfg = make_config(
        "lstman4", data_dir=args.data_dir,
        logdir=tempfile.mkdtemp(prefix="an4_trainmode_wer_"),
    )
    t = Trainer(cfg, profile_backward=False)
    ckpt = Checkpointer(args.checkpoint_dir)
    restored = ckpt.restore(t.state, epoch=args.epoch)
    if restored is None:
        raise SystemExit(f"no checkpoint under {args.checkpoint_dir}")
    state = restored.state
    epoch = restored.epoch
    variables = {
        "params": state.params, "batch_stats": state.batch_stats,
    }

    import jax

    @jax.jit
    def fwd(x, input_lengths):
        (logits, out_lengths), _ = t.model.apply(
            variables, x, input_lengths, train=True,
            mutable=["batch_stats"],
        )
        return logits, out_lengths

    from mgwfbp_tpu.data.audio import greedy_decode, ids_to_text

    total, n = 0.0, 0
    hyps = []
    for batch in t.bundle.val:
        logits, out_lengths = fwd(batch["x"], batch["input_lengths"])
        logits = np.asarray(logits)
        out_lengths = np.asarray(out_lengths)
        # canonical WER accounting: one shared path with the trainer's
        # fused eval (skips padded samples by the same predicate)
        w, k = t._decode_wer_batch(logits, out_lengths, batch)
        total += w
        n += k
        if len(hyps) < 5:
            ys = np.asarray(batch["y"])
            valid = np.asarray(batch["label_lengths"])
            for i, hyp in enumerate(greedy_decode(logits, out_lengths)):
                if valid[i] > 0 and len(hyps) < 5:
                    hyps.append(
                        {"ref": ids_to_text(ys[i, : valid[i]]), "hyp": hyp}
                    )
    out = {
        "train_mode_wer": round(total / max(n, 1), 4),
        "utterances": n,
        "epoch": epoch,
        "samples": hyps,
    }
    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
