#!/usr/bin/env bash
# Pre-PR gate: ruff -> static analysis -> tier-1 tests (ROADMAP.md).
# Any stage failing fails the script; ruff is skipped (with a notice) when
# the binary isn't installed, since the container image doesn't bake it in.
set -u -o pipefail

cd "$(dirname "$0")/.."
rc=0

echo "== [1/10] ruff =="
if command -v ruff >/dev/null 2>&1; then
    ruff check mgwfbp_tpu tests tools bench.py || rc=1
else
    echo "ruff not installed; skipping (config lives in pyproject.toml)"
fi

echo "== [2/10] mgwfbp_tpu.analysis (jit-safety lint -> THR race checker -> SPMD lockstep checker -> schedule verifier) =="
# cheapest-first inside the CLI: the THR host-concurrency pass and the
# RUN-family SPMD pass statically prove the threading and the multi-host
# protocol sound in ~1 s each, so a race/coordination bug fails HERE in
# seconds instead of hanging the multi-minute live smokes below into
# their hard timeouts; the zero-finding state of the shipped tree is
# pinned by this stage (ANA001 keeps the suppressions honest)
JAX_PLATFORMS=cpu python -m mgwfbp_tpu.analysis || rc=1
# the THR family's exit-code contract, end to end: a seeded
# unlocked-shared-buffer probe must fail with exactly bit 32
thr_probe="$(mktemp -t mgwfbp_thr_probe.XXXXXX.py)"
trap 'rm -f "$thr_probe"' EXIT
cat > "$thr_probe" <<'EOF'
import threading


class Buf:
    def __init__(self):
        self._rows = []
        self._t = threading.Thread(target=self._drain)
        self._t.start()

    def _drain(self):
        while True:
            self._rows.pop()

    def push(self, x):
        self._rows.append(x)
EOF
JAX_PLATFORMS=cpu python -m mgwfbp_tpu.analysis \
    --skip-lint --skip-spmd --skip-jaxpr "$thr_probe" >/dev/null 2>&1
thr_rc=$?
if [ "$thr_rc" -ne 32 ]; then
    echo "THR seeded probe exited $thr_rc, want 32 (family bit) — the race gate is not wired" >&2
    rc=1
fi

echo "== [3/10] telemetry report smoke (writer -> report -> exports) =="
JAX_PLATFORMS=cpu python tools/telemetry_report.py --selftest >/dev/null || rc=1

echo "== [4/10] fault-injection smoke (NaN skip + preempt/resume lifecycle) =="
JAX_PLATFORMS=cpu python tools/fault_smoke.py || rc=1

echo "== [5/10] async-checkpoint smoke (step-time envelope vs ckpt-off + async event contract) =="
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/fault_smoke.py --async-ckpt || rc=1

echo "== [6/10] multi-host smoke (2-process agreed drain -> supervisor resubmit -> resume; /fleet/status straggler table probed mid-run) =="
# hard timeout: a coordination bug's failure mode is a distributed HANG —
# and so is a fleet fan-in bug's — which must fail the gate, not wedge it
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/fault_smoke.py --processes 2 || rc=1

echo "== [7/10] elastic-resize smoke (supervisor-triggered drain -> relaunch at 1 process from the shard-native checkpoint -> resume to completion) =="
# same hard-timeout contract: a resize hang (re-shard deadlock, a child
# that never finds the sibling checkpoint) must FAIL the gate, not wedge it
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/fault_smoke.py --resize || rc=1

echo "== [8/10] serving-plane smoke (--serve-shadow run answers POST /predict mid-run; served step advances across mid-epoch commits; step-time envelope vs serve-off) =="
# same hard-timeout contract: a reload/dispatch hang must FAIL the gate
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/fault_smoke.py --serve || rc=1

echo "== [9/10] chaos smoke (SIGKILL mid-epoch -> shrink to survivors; wedge -> liveness heal in bounded time) =="
# same hard-timeout contract: an unhealed chaos fault's failure mode is a
# group that never finishes — the self-healing loop must land WELL inside
# this window or the gate fails
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/fault_smoke.py --chaos || rc=1

echo "== [10/10] tier-1 tests =="
t1log="$(mktemp -t mgwfbp_t1.XXXXXX.log)"  # private path: concurrent runs
trap 'rm -f "$t1log" "$thr_probe"' EXIT    # must not clobber each other
timeout -k 10 1260 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly 2>&1 | tee "$t1log"
t1=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$t1log" | tr -cd . | wc -c)"
[ "$t1" -ne 0 ] && rc=1

if [ "$rc" -eq 0 ]; then
    echo "check.sh: ALL GREEN"
else
    echo "check.sh: FAILURES (see above)" >&2
fi
exit "$rc"
