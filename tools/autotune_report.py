"""Autotune cache-entry report: solved vs measured group times + refit deltas.

Reads one committed schedule-cache entry (written by the closed-loop
autotuner, mgwfbp_tpu/parallel/autotune.py) and prints:

  * the committed winner (label, comm_op, groups, measured step time);
  * the race table — every candidate that was verified/raced, with its
    predicted and measured step times;
  * per-group solved-vs-measured times (measured column present only when
    the backend's profiler trace attributed group scopes — on the CPU mesh
    the refit runs from step-time deltas and the column reads n/a);
  * the cost-model refit: alpha/beta/gamma/update_beta before -> after.

Usage:
  python tools/autotune_report.py profiles/schedule_cache/<key>.json
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fmt_s(v) -> str:
    return f"{v:.6g}" if isinstance(v, (int, float)) and v is not None else "n/a"


def _delta_pct(before, after) -> str:
    try:
        if before:
            return f"{(after - before) / before * 100.0:+.1f}%"
    except TypeError:
        pass
    return "n/a"


def format_report(entry: dict) -> str:
    lines: list[str] = []
    key = entry.get("key", "?")
    lines.append(
        f"autotune cache entry: {key} "
        f"(model={entry.get('model')}, world={entry.get('world')}, "
        f"comm_op={entry.get('comm_op')}, dtype={entry.get('dtype')})"
    )
    cross = " [cross-step]" if entry.get("comm_op") == "rs_fwd_ag" else ""
    lines.append(
        f"committed winner: {entry.get('winner')}{cross} — "
        f"{len(entry.get('groups', []))} group(s), "
        f"measured {_fmt_s(entry.get('measured_step_s'))} s/step"
    )

    lines.append("")
    lines.append("race:")
    lines.append(
        f"  {'label':<40} {'groups':>6} {'verified':>8} "
        f"{'predicted_s':>12} {'measured_s':>12}"
    )
    for r in entry.get("race", []):
        label = r.get("label", "?")
        if r.get("comm_op") == "rs_fwd_ag":
            # cross-step candidate: its AG legs ride the NEXT step's
            # forward (one-step deferred gathers), priced by the
            # two-phase simulate
            label += " [cross-step]"
        lines.append(
            f"  {label:<40} {r.get('num_groups', 0):>6} "
            f"{str(r.get('verified', False)):>8} "
            f"{_fmt_s(r.get('predicted_total_s')):>12} "
            f"{_fmt_s(r.get('measured_step_s')):>12}"
        )

    solved = entry.get("solved_group_times") or []
    measured = entry.get("measured_group_times")
    lines.append("")
    lines.append("group times (committed schedule):")
    lines.append(
        f"  {'group':>5} {'bytes':>12} {'solved_s':>12} {'measured_s':>12}"
    )
    for gi, (nbytes, pred) in enumerate(solved):
        m = measured[gi] if measured and gi < len(measured) else None
        lines.append(
            f"  {gi:>5} {int(nbytes):>12} {_fmt_s(pred):>12} {_fmt_s(m):>12}"
        )
    if not measured:
        lines.append(
            "  (no per-group trace attribution on this backend; "
            "refit used step-time deltas)"
        )

    refit = entry.get("refit")
    lines.append("")
    if refit:
        before, after = refit.get("before", {}), refit.get("after", {})
        lines.append(f"cost-model refit (observations: {refit.get('source')}):")
        for k in ("alpha", "beta", "gamma", "pack_beta", "update_beta"):
            b, a = before.get(k), after.get(k)
            lines.append(
                f"  {k:<12} {_fmt_s(b):>12} -> {_fmt_s(a):>12}  "
                f"{_delta_pct(b, a)}"
            )
    else:
        lines.append("cost-model refit: none recorded")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="autotune_report",
        description="print solved-vs-measured group times and refit deltas "
        "from an autotune schedule-cache entry",
    )
    p.add_argument("entry", help="path to a schedule_cache/<key>.json entry")
    args = p.parse_args(argv)
    # the canonical reader: same schema validation as the autotuner itself
    from mgwfbp_tpu.parallel.autotune import load_cache_entry

    try:
        entry = load_cache_entry(args.entry)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 1
    if entry is None:
        print(f"{args.entry}: no such cache entry", file=sys.stderr)
        return 1
    print(format_report(entry))
    return 0


if __name__ == "__main__":
    sys.exit(main())
