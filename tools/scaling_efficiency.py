"""Weak-scaling efficiency harness (SURVEY.md §7 step 7; BASELINE.md north
star: >= 90% linear scaling efficiency at v5e-64).

Two parts, one committed JSON artifact:

  measured  — sec/iter of the production train step at data extents
              {1, 2, 4, ...} over the AVAILABLE devices (8-device virtual CPU
              mesh, or however many real chips exist), per-device batch held
              constant (weak scaling, reference dl_trainer.py:153-156).
              efficiency(n) = t(1) / t(n): 1.0 is perfect weak scaling.

  predicted — solver-simulated efficiency at TARGET TPU topologies the
              current host cannot provide (v5e-4 / v5e-16 single slice over
              ICI, v5e-64 as 4 slices x 16 chips via the two-level ICI+DCN
              model), per policy: efficiency = t_step(1) / (t_step(1) +
              predicted nonoverlapped comm). Uses the tb profile and
              t_step(1) measured HERE, so run this on the real chip for TPU
              predictions (CPU tb would mis-scale them). The same simulator
              drives the merge solver itself (parallel/solver.py
              simulate_groups), so these numbers are exactly what the
              framework believes — the honest stand-in until multi-chip
              hardware is reachable.

Usage:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python tools/scaling_efficiency.py --model resnet20 --batch 8 \
      --comm-profile profiles/cpu8_mesh.json --out profiles/scaling_cpu8.json
  python tools/scaling_efficiency.py --model resnet50 --batch 32 \
      --targets v5e-4,v5e-16,v5e-64 --out profiles/scaling_tpu_v5e_pred.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

POLICIES = ("mgwfbp", "auto", "wfbp", "single")


def _measure_step(model, meta, tx, mesh, reducer, batch, compute_dtype,
                  iters, warmup):
    """Best-of-3-window sec/iter of the jitted step (policy-grid protocol)."""
    import jax

    from mgwfbp_tpu.train import create_train_state, make_train_step

    import jax.numpy as jnp

    state = create_train_state(
        jax.random.PRNGKey(0), model,
        jnp.zeros((1,) + tuple(meta.input_shape), meta.input_dtype), tx,
    )
    step = make_train_step(
        model, meta, tx, mesh, reducer, compute_dtype=compute_dtype,
        donate=True,
    )
    for _ in range(max(warmup, 1)):  # >=1: compile + sync anchor
        state, m = step(state, batch)
    float(m["loss"])
    windows = []
    per_window = max(iters // 3, 1)
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(per_window):
            state, m = step(state, batch)
        float(m["loss"])  # one host pull per window brackets the window
        windows.append((time.perf_counter() - t0) / per_window)
    del state, step
    return min(windows)


def run(model_name, batch, policy, comm_profile, targets, iters, warmup,
        dtype_name):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mgwfbp_tpu import models as zoo
    from mgwfbp_tpu.optim import make_optimizer
    from mgwfbp_tpu.parallel.allreduce import arrival_order, make_merged_allreduce
    from mgwfbp_tpu.parallel.costmodel import (
        TwoLevelAlphaBeta, load_profile, lookup_alpha_beta,
    )
    from mgwfbp_tpu.parallel.mesh import DATA_AXIS, MeshSpec, make_mesh
    from mgwfbp_tpu.parallel.solver import LayerSpec, build_schedule
    from mgwfbp_tpu.profiling import benchmark_trainer_backward
    from mgwfbp_tpu.train import create_train_state

    compute_dtype = (
        None if dtype_name in ("float32", "f32") else jnp.dtype(dtype_name)
    )
    model, meta = zoo.create_model(model_name)
    tx, _ = make_optimizer(
        0.01, momentum=0.9, weight_decay=1e-4, lr_schedule="const",
        dataset=meta.dataset, num_batches_per_epoch=1,
    )
    state = create_train_state(
        jax.random.PRNGKey(0), model,
        jnp.zeros((1,) + tuple(meta.input_shape), meta.input_dtype), tx,
    )
    paths = jax.tree_util.tree_flatten_with_path(state.params)[0]
    names = [jax.tree_util.keystr(kp) for kp, _ in paths]
    leaves = [v for _, v in paths]
    perm = arrival_order(len(names), names=names)

    rs = np.random.RandomState(0)

    def make_batch(n_dev):
        gb = batch * n_dev
        shape = (1, gb) + tuple(meta.input_shape)
        return {
            "x": jnp.asarray(rs.randn(*shape)).astype(meta.input_dtype),
            "y": jnp.asarray(
                rs.randint(0, meta.num_classes, (1, gb)), jnp.int32
            ),
        }

    # tb: measured per-arrival backward profile at the per-device batch
    micro_batch = make_batch(1)
    micro = {k: v[0] for k, v in micro_batch.items()}
    tb = benchmark_trainer_backward(
        model, meta, state.params, state.batch_stats, micro, perm,
        warmup=2, iters=5, names=names, compute_dtype=compute_dtype,
    )

    flat_model = load_profile(comm_profile) if comm_profile else None

    # ---- measured weak scaling over the available devices
    avail = len(jax.devices())
    extents = [n for n in (1, 2, 4, 8, 16, 32) if n <= avail]
    measured = {}
    t1 = None
    for n in extents:
        mesh = make_mesh(MeshSpec(data=n), devices=jax.devices()[:n])
        if n == 1:
            reducer = None  # no communication exists on one device
        else:
            # ADVICE r3: a profile calibrated at ONE world size must not be
            # reused verbatim at every extent. Family profiles resolve per
            # extent (measured trend); a flat profile is resolved as-is and
            # the artifact records that caveat.
            from mgwfbp_tpu.parallel.costmodel import resolve_profile

            cm = (
                resolve_profile(flat_model, n)
                if flat_model is not None
                else lookup_alpha_beta("ici", n)
            )
            reducer = make_merged_allreduce(
                state.params, axis_name=DATA_AXIS, policy=policy, tb=tb,
                cost_model=cm,
            )
        dt = _measure_step(
            model, meta, tx, mesh, reducer, make_batch(n), compute_dtype,
            iters, warmup,
        )
        if n == 1:
            t1 = dt
        measured[str(n)] = {
            "sec_per_iter": round(dt, 6),
            "samples_per_sec": round(batch * n / dt, 2),
            "efficiency": round(t1 / dt, 4),
            "merge_groups": (
                reducer.schedule.num_groups if reducer is not None else 0
            ),
        }

    # ---- predicted efficiency at target TPU topologies (solver simulation)
    def target_cost(tname):
        if tname == "v5e-4":
            return lookup_alpha_beta("ici", 4), 4
        if tname == "v5e-16":
            return lookup_alpha_beta("ici", 16), 16
        if tname == "v5e-64":
            return (
                TwoLevelAlphaBeta(
                    ici=lookup_alpha_beta("ici", 16),
                    dcn=lookup_alpha_beta("dcn", 4),
                    ici_size=16,
                    dcn_size=4,
                ),
                64,
            )
        raise ValueError(f"unknown target {tname!r}")

    itemsize = 2 if compute_dtype == jnp.bfloat16 else 4
    layers = [
        LayerSpec(
            name=names[j], size=int(leaves[j].size), itemsize=itemsize
        )
        for j in perm
    ]
    tb_arrival = list(tb)
    predicted = {}
    for tname in targets:
        cm, nchips = target_cost(tname)
        per_policy = {}
        for pol in POLICIES:
            sched = build_schedule(
                layers, tb_arrival, policy=pol, cost_model=cm,
            )
            nonoverlap = sched.predicted_nonoverlap_time
            per_policy[pol] = {
                "merge_groups": sched.num_groups,
                "predicted_nonoverlap_s": round(nonoverlap, 8),
                "predicted_efficiency": round(t1 / (t1 + nonoverlap), 4),
            }
        predicted[tname] = {"n_chips": nchips, "policies": per_policy}

    return {
        "model": model_name,
        "batch_per_device": batch,
        "policy_measured": policy,
        "compute_dtype": dtype_name,
        "device_kind": jax.devices()[0].device_kind,
        "available_devices": avail,
        "comm_profile": comm_profile,
        "comm_profile_kind": (
            None if flat_model is None else type(flat_model).__name__
        ),
        "comm_profile_note": (
            None
            if flat_model is None
            else (
                "family profile: alpha-beta-gamma resolved per measured "
                "extent (log2 interpolation between calibrated world sizes)"
                if type(flat_model).__name__ == "ProfileFamily"
                else "flat profile calibrated at one world size, applied "
                "AS-IS at every measured extent (no alpha-vs-hops rescale); "
                "prefer a --world-sizes family calibration"
            )
        ),
        "tb_total_s": round(sum(tb), 6),
        "t1_sec_per_iter": round(t1, 6),
        "measured_weak_scaling": measured,
        "predicted_targets": predicted,
        "method": (
            "weak scaling: per-device batch fixed, efficiency = t(1)/t(n); "
            "predictions: efficiency = t1/(t1 + solver-simulated "
            "nonoverlapped comm) per policy, the same simulate_groups the "
            "merge solver optimizes. 'ici'/'dcn' cost models are priors "
            "unless --comm-profile supplies a calibration."
        ),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="resnet20")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--policy", default="mgwfbp")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--comm-profile", dest="comm_profile", default=None)
    ap.add_argument("--targets", default="v5e-4,v5e-16,v5e-64")
    ap.add_argument("--note", default=None)
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    from mgwfbp_tpu.utils.platform import apply_platform_overrides

    apply_platform_overrides()
    report = run(
        args.model, args.batch, args.policy, args.comm_profile,
        [t for t in args.targets.split(",") if t], args.iters, args.warmup,
        args.dtype,
    )
    if args.note:
        report["environment_note"] = args.note
    text = json.dumps(report, indent=2)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            f.write(text)
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
