"""Validate the two-level (ICI+DCN) cost model against measurement
(VERDICT r4 #8).

`costmodel.TwoLevelAlphaBeta` prices a hierarchical bucket all-reduce as
ici(full payload) + dcn(payload / ici_size) — the reduce-scatter(inner) ->
all-reduce(outer) -> all-gather(inner) lowering of
`allreduce._hierarchical_allreduce`. Until now that model was only
correctness-tested; this tool checks its PREDICTIONS on a mesh where both
levels are real collectives: the virtual CPU mesh shaped (ici, dcn).

Protocol:
  1. Calibrate per-axis AlphaBeta by timing a pmean over ONLY the inner
     axis and ONLY the outer axis, payload-swept (the per-axis analogue of
     `profiling.profile_allreduce`).
  2. Time the actual `hier` lowering and the flat both-axes pmean over the
     same payloads.
  3. Compare TwoLevelAlphaBeta predictions against the measured hier
     times; record per-size gaps. Also record hier-vs-flat so the artifact
     says when the explicit hierarchy beats XLA's flat lowering here.

Caveat recorded in the artifact: on the virtual CPU mesh both "levels"
are the same memory fabric, so ici/dcn constants differ only by group
size/contention — the check validates the MODEL'S COMPOSITION (that
hier cost = inner term on full payload + outer term on the shard), not
real DCN physics.

Usage:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python tools/two_level_validation.py --ici 4 --dcn 2 \
    --out profiles/two_level_cpu.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _time_fn(fn, x, warmup, iters):
    for _ in range(warmup):
        fn(x).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        fn(x).block_until_ready()
    return (time.perf_counter() - t0) / iters


def run(ici, dcn, min_log2, max_log2, warmup, iters):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    from mgwfbp_tpu.parallel.allreduce import _hierarchical_allreduce
    from mgwfbp_tpu.parallel.costmodel import (
        SampledCost, TwoLevelAlphaBeta, fit_alpha_beta,
    )
    from mgwfbp_tpu.utils.platform import get_shard_map

    shard_map = get_shard_map()

    n = ici * dcn
    devs = np.asarray(jax.devices()[:n]).reshape(ici, dcn)
    mesh = Mesh(devs, ("ici", "dcn"))
    sizes = [2 ** k for k in range(min_log2, max_log2 + 1)]
    itemsize = 4

    def timed(body):
        fn = jax.jit(
            shard_map(
                body, mesh=mesh, in_specs=P(), out_specs=P(),
                check_vma=False,
            )
        )
        return {
            s: _time_fn(fn, jnp.ones((s,), jnp.float32), warmup, iters)
            for s in sizes
        }

    t_ici = timed(lambda x: lax.pmean(x, "ici"))
    t_dcn = timed(lambda x: lax.pmean(x, "dcn"))
    t_flat = timed(lambda x: lax.pmean(x, ("ici", "dcn")))
    t_hier = timed(
        lambda x: _hierarchical_allreduce(x, "ici", "dcn", mean=True)
    )
    # dispatch baseline: a jitted no-collective program over the same
    # payload. Each standalone per-axis timing above bakes one program
    # dispatch + output materialization into its curve; the fused hier
    # program pays that once, so naive composition double-counts it (the
    # production calibration separates this as gamma for the same reason).
    t_id = timed(lambda x: x * 1.0)

    nbytes = [s * itemsize for s in sizes]
    ab_ici = fit_alpha_beta(nbytes, [t_ici[s] for s in sizes])
    ab_dcn = fit_alpha_beta(nbytes, [t_dcn[s] for s in sizes])
    model = TwoLevelAlphaBeta(
        ici=ab_ici, dcn=ab_dcn, ici_size=ici, dcn_size=dcn
    )
    # the production-grade predictor: SampledCost curves per level (a
    # single alpha-beta line cannot describe this mesh's cache-regime
    # nonlinearity — same reason flat calibrations persist sampled
    # curves). TwoLevelAlphaBeta composes by duck-typed .predict, so the
    # sampled members exercise the same composition rule.
    sc_ici = SampledCost(tuple(nbytes), tuple(t_ici[s] for s in sizes),
                         ab=ab_ici)
    sc_dcn = SampledCost(tuple(nbytes), tuple(t_dcn[s] for s in sizes),
                         ab=ab_dcn)
    sc_id = SampledCost(
        tuple(nbytes), tuple(t_id[s] for s in sizes),
        ab=fit_alpha_beta(nbytes, [t_id[s] for s in sizes]),
    )
    model_sampled = TwoLevelAlphaBeta(
        ici=sc_ici, dcn=sc_dcn, ici_size=ici, dcn_size=dcn
    )

    rows = []
    gaps_ab, gaps_sc, gaps_corr = [], [], []
    for s in sizes:
        b = s * itemsize
        pred_ab = model.predict(b)
        pred_sc = model_sampled.predict(b)
        # dispatch-corrected composition: the two phase curves carry two
        # program dispatches, the fused program pays one — subtract the
        # smaller phase's no-op program time
        pred_corr = pred_sc - sc_id.predict(b / max(ici, 1))
        meas = t_hier[s]
        gap_ab = (pred_ab - meas) / meas
        gap_sc = (pred_sc - meas) / meas
        gap_corr = (pred_corr - meas) / meas
        gaps_ab.append(abs(gap_ab))
        gaps_sc.append(abs(gap_sc))
        gaps_corr.append(abs(gap_corr))
        rows.append({
            "payload_bytes": b,
            "measured_ici_only_s": round(t_ici[s], 6),
            "measured_dcn_only_s": round(t_dcn[s], 6),
            "measured_noop_s": round(t_id[s], 6),
            "measured_hier_s": round(meas, 6),
            "measured_flat_s": round(t_flat[s], 6),
            "predicted_hier_ab_fit_s": round(pred_ab, 6),
            "predicted_hier_sampled_s": round(pred_sc, 6),
            "predicted_hier_dispatch_corrected_s": round(pred_corr, 6),
            "prediction_gap_ab_fit_frac": round(gap_ab, 4),
            "prediction_gap_sampled_frac": round(gap_sc, 4),
            "prediction_gap_corrected_frac": round(gap_corr, 4),
            "hier_vs_flat": round(meas / t_flat[s], 4),
        })
    return model, {
        "mesh": {"ici": ici, "dcn": dcn},
        "device_kind": jax.devices()[0].device_kind,
        "warmup": warmup,
        "iters": iters,
        "fit": {
            "ici": {"alpha": ab_ici.alpha, "beta": ab_ici.beta},
            "dcn": {"alpha": ab_dcn.alpha, "beta": ab_dcn.beta},
        },
        "rows": rows,
        # the composition check proper: measured per-level curves composed
        # as ici(full) + dcn(shard), vs the measured hier lowering
        "median_abs_gap_sampled_frac": round(float(np.median(gaps_sc)), 4),
        "max_abs_gap_sampled_frac": round(float(np.max(gaps_sc)), 4),
        # same, minus the double-counted program dispatch (the fused hier
        # program dispatches once; two standalone phase timings carry two)
        "median_abs_gap_corrected_frac": round(
            float(np.median(gaps_corr)), 4
        ),
        "max_abs_gap_corrected_frac": round(float(np.max(gaps_corr)), 4),
        # the 2-parameter summary's gap, recorded so the artifact shows why
        # production profiles persist sampled curves, not lines
        "median_abs_gap_ab_fit_frac": round(float(np.median(gaps_ab)), 4),
        "median_hier_vs_flat": round(
            float(np.median([r["hier_vs_flat"] for r in rows])), 4
        ),
        "caveat": (
            "virtual CPU mesh: both levels share one memory fabric, so "
            "this validates the model's COMPOSITION (inner term on full "
            "payload + outer term on the 1/ici_size shard), not DCN "
            "physics"
        ),
        "finding": (
            "dispatch-corrected composition tracks the measured hier "
            "lowering within ~20% at small and large payloads; mid-size "
            "residuals (where the fused program overlaps the two phases' "
            "memory traffic across cores, which a sequential-composition "
            "model cannot price) stay under ~60%. On real ICI+DCN the "
            "phases traverse DIFFERENT wires, so the sequential-"
            "composition assumption is better there than on this shared "
            "fabric. hier_vs_flat > 1 throughout: on a single-fabric mesh "
            "the explicit hierarchy only adds steps — consistent with the "
            "model, which prices hier above flat whenever the outer level "
            "is not much slower than the inner"
        ),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ici", type=int, default=4)
    ap.add_argument("--dcn", type=int, default=2)
    ap.add_argument("--min-log2", type=int, default=13)
    ap.add_argument("--max-log2", type=int, default=23)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    from mgwfbp_tpu.utils.platform import apply_platform_overrides

    apply_platform_overrides()
    from mgwfbp_tpu.parallel.costmodel import save_profile

    model, report = run(
        args.ici, args.dcn, args.min_log2, args.max_log2,
        args.warmup, args.iters,
    )
    text = json.dumps(report, indent=2)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        save_profile(args.out, model, meta=report)
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
