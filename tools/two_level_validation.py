"""Validate the two-level (ICI+DCN) cost model against measurement
(VERDICT r4 #8; thin consumer of `profiling.profile_two_level` since the
per-axis calibration moved there for `calibrate --two-level`).

Two checks on a mesh where both levels are real collectives — the virtual
CPU mesh shaped (ici, dcn):

  1. COMPOSITION (the original r4 check): `costmodel.TwoLevelAlphaBeta`
     prices a hierarchical bucket all-reduce as ici(full payload) +
     dcn(payload / ici_size). Time the actual hier lowering and the flat
     both-axes pmean over the calibration's payloads and record per-size
     prediction gaps (raw and dispatch-corrected — the two standalone
     phase sweeps carry two program dispatches, the fused program one).
  2. SOLVED SCHEDULE (ISSUE 11): the two-link solver's output, not just a
     single bucket. Solve a synthetic layer set with
     `auto_groups_two_level` (nested inner/DCN partitions), lower it via
     the real `make_merged_allreduce(comm_op='hier')`, and time it against
     the flat single-link solve under the all_reduce lowering — the
     hier-vs-flat race the autotuner runs live, measured offline.

Caveat recorded in the artifact: on the virtual CPU mesh both "levels"
are the same memory fabric, so ici/dcn constants differ only by group
size/contention — the check validates the MODEL'S COMPOSITION and the
SOLVER'S MACHINERY, not real DCN physics.

Usage:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python tools/two_level_validation.py --ici 4 --dcn 2 \
    --out profiles/two_level_cpu.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _time_fn(fn, x, warmup, iters):
    for _ in range(warmup):
        fn(x).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        fn(x).block_until_ready()
    return (time.perf_counter() - t0) / iters


def _solved_schedule_check(model, raw, warmup, iters):
    """Race the SOLVED nested hier schedule against the flat single-link
    solve, both lowered for real on the calibration mesh."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from mgwfbp_tpu.parallel.allreduce import make_merged_allreduce
    from mgwfbp_tpu.parallel.solver import (
        auto_groups,
        simulate_groups,
        simulate_groups_two_level,
        singleton_dcn_groups,
        two_level_leg_costs,
    )
    from mgwfbp_tpu.utils.platform import get_shard_map

    shard_map = get_shard_map()
    mesh = raw["mesh"]
    inner, outer = raw["inner_axis"], raw["outer_axis"]

    # synthetic model: a dozen mixed-size layers, backward profile from
    # the parameter-volume prior at a scale where merging decisions are
    # live (the regime the win condition cares about)
    rs = np.random.RandomState(0)
    sizes = [int(s) for s in rs.choice(
        [1 << 14, 1 << 16, 1 << 18], size=12
    )]
    tb_total = model.predict(float(sum(sizes)) * 4)
    tb = [tb_total * s / sum(sizes) for s in sizes]
    tree = {
        f"layer{i:02d}": {"w": jnp.asarray(rs.randn(s), jnp.float32)}
        for i, s in enumerate(sizes)
    }
    nbytes = [s * 4 for s in sizes]

    hier_red = make_merged_allreduce(
        tree, axis_name=(inner, outer), policy="auto", comm_op="hier",
        tb=tb, cost_model=model,
    )
    flat_groups, flat_detail = auto_groups(
        sizes, tb, alpha=model.alpha, cost=model.predict,
    )
    flat_red = make_merged_allreduce(
        tree, axis_name=(inner, outer), policy="auto", comm_op="all_reduce",
        tb=tb, cost_model=model, groups=flat_groups,
        policy_detail=flat_detail,
    )

    def timed(red):
        fn = jax.jit(shard_map(
            lambda t: red(t), mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False,
        ))
        for _ in range(warmup):
            jax.block_until_ready(fn(tree))
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(tree)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters

    t_hier = timed(hier_red)
    t_flat = timed(flat_red)
    rs_c, dcn_c, ag_c = two_level_leg_costs(model)
    pred_hier, _, _ = simulate_groups_two_level(
        hier_red.schedule.groups, hier_red.schedule.dcn_groups, nbytes, tb,
        rs_c, dcn_c, ag_c,
    )
    pred_flat, _, _ = simulate_groups(
        flat_red.schedule.groups, nbytes, tb, model.predict,
    )
    pred_hier_singleton, _, _ = simulate_groups_two_level(
        hier_red.schedule.groups,
        singleton_dcn_groups(len(hier_red.schedule.groups)),
        nbytes, tb, rs_c, dcn_c, ag_c,
    )
    return {
        "layer_sizes": sizes,
        "hier": {
            "detail": hier_red.schedule.policy_detail,
            "groups": [list(g) for g in hier_red.schedule.groups],
            "dcn_groups": [list(d) for d in hier_red.schedule.dcn_groups],
            "predicted_s": round(float(pred_hier), 6),
            "predicted_singleton_dcn_s": round(
                float(pred_hier_singleton), 6
            ),
            "measured_s": round(t_hier, 6),
        },
        "flat": {
            "detail": flat_detail,
            "groups": [list(g) for g in flat_red.schedule.groups],
            "predicted_s": round(float(pred_flat), 6),
            "measured_s": round(t_flat, 6),
        },
        "solved_hier_vs_flat_measured": round(t_hier / t_flat, 4),
        "solved_hier_vs_flat_predicted": round(
            float(pred_hier) / float(pred_flat), 4
        ),
    }


def run(ici, dcn, min_log2, max_log2, warmup, iters):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from mgwfbp_tpu.parallel.allreduce import _hierarchical_allreduce
    from mgwfbp_tpu.parallel.costmodel import SampledCost, fit_alpha_beta
    from mgwfbp_tpu.profiling import profile_two_level
    from mgwfbp_tpu.utils.platform import get_shard_map

    shard_map = get_shard_map()

    # step 1: per-axis calibration — the shared engine behind
    # `calibrate --two-level` (this tool only CONSUMES it now)
    sizes = [2 ** k for k in range(min_log2, max_log2 + 1)]
    model_sampled, raw = profile_two_level(
        ici, dcn, sizes=sizes, warmup=warmup, iters=iters,
        noop_baseline=True,  # the dispatch correction's baseline
    )
    mesh = raw["mesh"]
    inner, outer = raw["inner_axis"], raw["outer_axis"]
    t_ici = raw["ici_s"]
    t_dcn = raw["dcn_s"]
    t_id = raw["noop_s"]
    nbytes = raw["sizes_bytes"]
    ab_ici = model_sampled.ici.ab
    ab_dcn = model_sampled.dcn.ab
    from mgwfbp_tpu.parallel.costmodel import TwoLevelAlphaBeta

    model = TwoLevelAlphaBeta(
        ici=ab_ici, dcn=ab_dcn, ici_size=ici, dcn_size=dcn
    )
    sc_id = SampledCost(
        tuple(nbytes), tuple(t_id[b] for b in nbytes),
        ab=fit_alpha_beta(nbytes, [t_id[b] for b in nbytes]),
    )

    # step 2: measure the actual hier lowering + the flat both-axes pmean
    def timed(body):
        fn = jax.jit(
            shard_map(
                body, mesh=mesh, in_specs=P(), out_specs=P(),
                check_vma=False,
            )
        )
        return {
            b: _time_fn(
                fn, jnp.ones((b // 4,), jnp.float32), warmup, iters
            )
            for b in nbytes
        }

    t_flat = timed(lambda x: lax.pmean(x, (inner, outer)))
    t_hier = timed(
        lambda x: _hierarchical_allreduce(x, inner, outer, mean=True)
    )

    rows = []
    gaps_ab, gaps_sc, gaps_corr = [], [], []
    for b in nbytes:
        pred_ab = model.predict(b)
        pred_sc = model_sampled.predict(b)
        # dispatch-corrected composition: the two phase curves carry two
        # program dispatches, the fused program pays one — subtract the
        # smaller phase's no-op program time
        pred_corr = pred_sc - sc_id.predict(b / max(ici, 1))
        meas = t_hier[b]
        gap_ab = (pred_ab - meas) / meas
        gap_sc = (pred_sc - meas) / meas
        gap_corr = (pred_corr - meas) / meas
        gaps_ab.append(abs(gap_ab))
        gaps_sc.append(abs(gap_sc))
        gaps_corr.append(abs(gap_corr))
        rows.append({
            "payload_bytes": b,
            "measured_ici_only_s": round(t_ici[b], 6),
            "measured_dcn_only_s": round(t_dcn[b], 6),
            "measured_noop_s": round(t_id[b], 6),
            "measured_hier_s": round(meas, 6),
            "measured_flat_s": round(t_flat[b], 6),
            "predicted_hier_ab_fit_s": round(pred_ab, 6),
            "predicted_hier_sampled_s": round(pred_sc, 6),
            "predicted_hier_dispatch_corrected_s": round(pred_corr, 6),
            "prediction_gap_ab_fit_frac": round(gap_ab, 4),
            "prediction_gap_sampled_frac": round(gap_sc, 4),
            "prediction_gap_corrected_frac": round(gap_corr, 4),
            "hier_vs_flat": round(meas / t_flat[b], 4),
        })

    # step 3 (ISSUE 11): validate the SOLVED hier schedule, not just
    # single-bucket composition — the two-link solver's nested output
    # lowered for real and raced against the flat single-link solve
    solved = _solved_schedule_check(model_sampled, raw, warmup, iters)

    return model_sampled, {
        "mesh": {"ici": ici, "dcn": dcn},
        "device_kind": jax.devices()[0].device_kind,
        "warmup": warmup,
        "iters": iters,
        "fit": raw["fit"],
        "rows": rows,
        # the composition check proper: measured per-level curves composed
        # as ici(full) + dcn(shard), vs the measured hier lowering
        "median_abs_gap_sampled_frac": round(float(np.median(gaps_sc)), 4),
        "max_abs_gap_sampled_frac": round(float(np.max(gaps_sc)), 4),
        # same, minus the double-counted program dispatch (the fused hier
        # program dispatches once; two standalone phase timings carry two)
        "median_abs_gap_corrected_frac": round(
            float(np.median(gaps_corr)), 4
        ),
        "max_abs_gap_corrected_frac": round(float(np.max(gaps_corr)), 4),
        # the 2-parameter summary's gap, recorded so the artifact shows why
        # production profiles persist sampled curves, not lines
        "median_abs_gap_ab_fit_frac": round(float(np.median(gaps_ab)), 4),
        "median_hier_vs_flat": round(
            float(np.median([r["hier_vs_flat"] for r in rows])), 4
        ),
        "solved_schedule": solved,
        "caveat": (
            "virtual CPU mesh: both levels share one memory fabric, so "
            "this validates the model's COMPOSITION (inner term on full "
            "payload + outer term on the 1/ici_size shard) and the "
            "two-link solver's machinery, not DCN physics"
        ),
        "finding": (
            "dispatch-corrected composition tracks the measured hier "
            "lowering within ~20% at small and large payloads; mid-size "
            "residuals (where the fused program overlaps the two phases' "
            "memory traffic across cores, which a sequential-composition "
            "model cannot price) stay under ~60%. On real ICI+DCN the "
            "phases traverse DIFFERENT wires, so the sequential-"
            "composition assumption is better there than on this shared "
            "fabric. hier_vs_flat > 1 throughout: on a single-fabric mesh "
            "the explicit hierarchy only adds steps — consistent with the "
            "model, which prices hier above flat whenever the outer level "
            "is not much slower than the inner; the solved_schedule "
            "section measures the same ranking for the SOLVED nested "
            "schedule, which is the live autotune race's offline twin"
        ),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ici", type=int, default=4)
    ap.add_argument("--dcn", type=int, default=2)
    ap.add_argument("--min-log2", type=int, default=13)
    ap.add_argument("--max-log2", type=int, default=23)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    from mgwfbp_tpu.utils.platform import apply_platform_overrides

    apply_platform_overrides()
    from mgwfbp_tpu.parallel.costmodel import save_profile

    model, report = run(
        args.ici, args.dcn, args.min_log2, args.max_log2,
        args.warmup, args.iters,
    )
    text = json.dumps(report, indent=2)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        save_profile(args.out, model, meta=report)
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
