"""Telemetry-stream report: overlap table + run trend from one events file.

Reads a run's telemetry JSONL (written by `mgwfbp_tpu.telemetry`, enabled
with ``--telemetry`` on the train CLI) and prints:

  * the run header (model/world/comm_op/policy);
  * the step-time trend — span count, mean/min/max seconds per step, first
    vs last 10-span window (throughput drift over the run);
  * the per-merge-group exposed/hidden comm table from the latest overlap
    snapshot, with the attribution source (``trace`` on backends whose op
    metadata keeps the `mgwfbp_groupNNNN` scopes; ``cost-model`` on the
    CPU mesh, whose traces drop the name stack);
  * the aggregate overlap-efficiency number (hidden / total comm — the
    paper's headline metric);
  * the alarms table — cost-model drift rows (kind, merge group, residual
    vs band) and live straggler rows (slow process, excess) from the
    drift detector / multi-host probe (telemetry/drift.py), raise and
    clear edges both;
  * lifecycle events: resizes (and which schedule path won), checkpoints,
    autotune race rows, watchdog stalls, bench skips.

Optionally renders the same stream for external viewers:

  python tools/telemetry_report.py <run>/telemetry.jsonl
  python tools/telemetry_report.py <run>/telemetry.jsonl \
      --chrome-trace trace.json --prometheus metrics.prom
  python tools/telemetry_report.py --live http://host:port   # RUNNING job
  python tools/telemetry_report.py --selftest   # synthetic stream smoke

``--live`` renders the overlap/alarms/lifecycle view from a RUNNING
job's /status + /metrics endpoints (telemetry/serve.py) instead of JSONL
files; pointed at a supervisor's fleet fan-in it renders the group view
(/fleet/status: per-process table, live stragglers, fleet alarms).

``--selftest`` exercises the full pipeline (writer -> reader -> report ->
Chrome trace -> Prometheus) on a synthetic stream in a temp dir — the
standing-gate smoke tools/check.sh runs, no accelerator or dataset needed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fmt_s(v) -> str:
    return f"{v:.6g}" if isinstance(v, (int, float)) else "n/a"


def _window_mean(spans: list[dict], sl: slice) -> float:
    w = spans[sl]
    return sum(float(s["dur_s"]) for s in w) / max(len(w), 1)


def format_report(records: list[dict]) -> str:
    from mgwfbp_tpu.telemetry import events_of

    lines: list[str] = []
    header = next(iter(events_of(records, "header")), {})
    run = header.get("run", {}) or {}
    desc = ", ".join(f"{k}={v}" for k, v in sorted(run.items()))
    lines.append(
        f"telemetry stream: schema v{header.get('schema_version', '?')}"
        + (f" ({desc})" if desc else "")
    )

    steps = events_of(records, "step")
    if steps:
        durs = [float(s["dur_s"]) for s in steps]
        lines.append("")
        lines.append(
            f"steps: {len(steps)} spans, mean {_fmt_s(sum(durs)/len(durs))} "
            f"s/step (min {_fmt_s(min(durs))}, max {_fmt_s(max(durs))})"
        )
        if len(steps) >= 20:
            first = _window_mean(steps, slice(0, 10))
            last = _window_mean(steps, slice(-10, None))
            drift = (last - first) / first * 100.0 if first > 0 else 0.0
            lines.append(
                f"trend: first-10 {_fmt_s(first)} s -> last-10 "
                f"{_fmt_s(last)} s ({drift:+.1f}%)"
            )
    else:
        lines.append("steps: none recorded")

    from mgwfbp_tpu.telemetry.export import latest_snapshot

    snap, rows = latest_snapshot(records)
    if snap is not None:
        lines.append("")
        lines.append(
            f"overlap snapshot (step {snap.get('step')}, attribution="
            f"{snap.get('attribution')}):"
        )
        cross = float(snap.get("tf_total_s", 0.0) or 0.0) > 0.0
        hier = float(snap.get("dcn_s", 0.0) or 0.0) > 0.0
        lines.append(
            f"  {'group':>5} {'bytes':>12} {'comm_s':>10} {'hidden_s':>10} "
            f"{'exposed_s':>10}"
            + (f" {'ag_s':>10}" if cross else "")
            + (f" {'ici_s':>10} {'dcn_s':>10}" if hier else "")
        )
        for r in rows:
            row = (
                f"  {int(r['group']):>5} {int(r['nbytes']):>12} "
                f"{_fmt_s(r['comm_s']):>10} {_fmt_s(r['hidden_s']):>10} "
                f"{_fmt_s(r['exposed_s']):>10}"
            )
            if cross:
                # cross-step regime: ag_s is the deferred all-gather leg
                # riding the NEXT step's forward
                row += f" {_fmt_s(r.get('ag_s', 0.0)):>10}"
            if hier:
                # hierarchical regime: each group's comm split by LINK
                row += (
                    f" {_fmt_s(r.get('ici_s', 0.0)):>10} "
                    f"{_fmt_s(r.get('dcn_s', 0.0)):>10}"
                )
            lines.append(row)
        tail = (
            f"(forward {_fmt_s(snap.get('tf_total_s'))} s, backward "
            if cross
            else "(backward "
        )
        lines.append(
            f"  total comm {_fmt_s(snap.get('comm_s'))} s = hidden "
            f"{_fmt_s(snap.get('hidden_s'))} s + exposed "
            f"{_fmt_s(snap.get('exposed_s'))} s "
            + tail
            + f"{_fmt_s(snap.get('tb_total_s'))} s, step "
            f"{_fmt_s(snap.get('step_s'))} s)"
        )
        if cross:
            lines.append(
                "  cross-step regime (rs_fwd_ag): each group's AG is "
                "deferred into the next step's forward; hidden counts "
                "both forward- and backward-side overlap"
            )
        if hier:
            lines.append(
                "  hierarchical regime (hier): comm split by link — ici "
                f"{_fmt_s(snap.get('ici_s'))} s vs dcn "
                f"{_fmt_s(snap.get('dcn_s'))} s; bottleneck link: "
                f"{snap.get('bottleneck_link')}"
            )
        lines.append(
            f"overlap efficiency: {float(snap.get('efficiency', 0.0)):.4f} "
            "(hidden / total comm; 1.0 = fully hidden)"
        )
    else:
        lines.append("")
        lines.append("overlap: no snapshot recorded (single-device run, "
                     "policy 'none', or telemetry off during fit)")

    lines.extend(_health_section(records))
    lines.extend(_serving_section(records))

    alarms = events_of(records, "drift_alarm", "straggler", "health_alarm")
    if alarms:
        lines.append("")
        lines.append("alarms:")
        lines.append(
            f"  {'kind':>17} {'edge':>6} {'group/proc':>10} "
            f"{'residual':>10} {'band':>8} {'step':>8}"
        )
        for r in alarms:
            if r.get("event") == "drift_alarm":
                kind = str(r.get("kind"))
                who = (
                    str(r.get("group"))
                    if int(r.get("group", -1)) >= 0 else "agg"
                )
                residual = _fmt_s(r.get("residual"))
                band = _fmt_s(r.get("band"))
            elif r.get("event") == "health_alarm":
                kind = str(r.get("kind"))
                who = (
                    str(r.get("group"))
                    if int(r.get("group", -1)) >= 0 else "agg"
                )
                residual = _fmt_s(r.get("value"))
                band = _fmt_s(r.get("band"))
            else:
                kind = "straggler"
                who = f"p{r.get('slow_process')}"
                residual = _fmt_s(r.get("excess_s"))
                band = "-"
            lines.append(
                f"  {kind:>17} "
                f"{'RAISE' if r.get('active') else 'clear':>6} "
                f"{who:>10} {residual:>10} {band:>8} "
                f"{str(r.get('step', '-')):>8}"
            )

    lifecycle = []
    for ev, render in (
        ("resize", lambda r: (
            f"resize {r.get('old_world')} -> {r.get('new_world')} "
            f"({r.get('schedule_source')}, {r.get('num_groups')} groups)")),
        ("checkpoint", _ckpt_line),
        ("autotune_race", lambda r: (
            f"autotune race {r.get('label')}: "
            f"{_fmt_s(r.get('measured_step_s'))} s/step "
            f"({'verified' if r.get('verified') else 'rejected'})")),
        ("autotune_commit", lambda r: (
            f"autotune commit {r.get('winner')} "
            f"({r.get('comm_op')}, {r.get('num_groups')} groups, "
            f"source={r.get('source')})")),
        ("watchdog_stall", lambda r: (
            f"WATCHDOG STALL in {r.get('phase')!r} after "
            f"{_fmt_s(r.get('idle_s'))} s"
            + (" (aborted)" if r.get("abort") else ""))),
        ("bench_skip", lambda r: f"bench skipped: {r.get('detail')}"),
        ("bad_step", lambda r: (
            f"BAD STEP {r.get('step')} (epoch {r.get('epoch')}): "
            f"{_fmt_s(r.get('nonfinite'))} non-finite gradient element(s), "
            "update dropped")),
        ("rollback", lambda r: (
            f"ROLLBACK after {r.get('bad_steps')} consecutive bad steps "
            f"-> restored iter {r.get('restored_iteration')} "
            f"(epoch {r.get('restored_epoch')})")),
        ("preempt", lambda r: (
            f"PREEMPTED by {r.get('signal')} at epoch {r.get('epoch')} "
            f"iter {r.get('iteration')} (checkpointed, rc 75)")),
        ("resume", lambda r: (
            f"resumed at epoch {r.get('epoch')} iter {r.get('iteration')}"
            + (" (mid-epoch)" if r.get("mid_epoch") else " (boundary)"))),
        ("failure", lambda r: (
            f"FAILURE [{r.get('class')}] on {r.get('target')}"
            + (f" rc {r.get('rc')}" if r.get("rc") is not None else "")
            + (f" at step {r.get('step')}"
               if r.get("step") is not None else "")
            + (f" ({r.get('op')})" if r.get("op") else ""))),
        ("heal", lambda r: (
            f"HEAL {r.get('action')}"
            + (f" [{r.get('class')}]" if r.get("class") else "")
            + (f" {r.get('old_world')} -> {r.get('world')} proc(s)"
               if r.get("action") == "shrink"
               else (f" at world {r.get('world')}"
                     if r.get("world") is not None else ""))
            + (f" (reason: {r.get('reason')})" if r.get("reason") else "")
            + (f" ({r.get('restarts')} restart(s))"
               if r.get("restarts") is not None else ""))),
    ):
        for r in events_of(records, ev):
            lifecycle.append(render(r))
    if lifecycle:
        lines.append("")
        lines.append("lifecycle:")
        lines.extend(f"  {s}" for s in lifecycle)

    # checkpoint save-duration trend (ISSUE 16): creeping save cost is a
    # regression signal (state growth, fs contention), and a save whose
    # async payload write overlapped more than one optimizer step is
    # worth surfacing — that is the writer earning its keep, or, when
    # the overlap keeps growing, the writer falling behind the cadence
    saves = [
        r for r in events_of(records, "checkpoint")
        if r.get("duration_s") is not None
    ]
    if saves:
        durs = [float(r["duration_s"]) for r in saves]
        n_async = sum(1 for r in saves if r.get("async"))
        lines.append("")
        lines.append(
            f"checkpoint saves ({len(saves)}, {n_async} async):"
        )
        half = len(durs) // 2
        trend = ""
        if half >= 1 and len(durs) >= 4:
            early = sum(durs[:half]) / half
            late = sum(durs[half:]) / (len(durs) - half)
            trend = (
                f", trend {_fmt_s(early)} -> {_fmt_s(late)} s"
                + (" [REGRESSING]" if late > 1.5 * early else "")
            )
        lines.append(
            f"  duration mean {_fmt_s(sum(durs) / len(durs))} s, "
            f"max {_fmt_s(max(durs))} s{trend}"
        )
        for r in saves:
            ov = _ckpt_overlap_steps(r)
            if ov > 1:
                lines.append(
                    f"  save at iter {r.get('iteration')} overlapped "
                    f"{ov} steps (committed at iter "
                    f"{r.get('commit_iteration')})"
                )
    return "\n".join(lines)


def _ckpt_overlap_steps(r: dict) -> int:
    """Steps the async payload write spanned: submit iteration to commit
    iteration (0 for synchronous saves, which block the loop)."""
    if not r.get("async") or r.get("commit_iteration") is None:
        return 0
    return int(r["commit_iteration"]) - int(r.get("iteration", 0))


def _ckpt_line(r: dict) -> str:
    s = f"checkpoint epoch {r.get('epoch')} iter {r.get('iteration')}"
    if r.get("duration_s") is not None:
        s += (
            f" [{r.get('format')} {_fmt_s(r.get('duration_s'))} s, "
            f"{int(r.get('bytes', 0)) // 1024} KiB/proc]"
        )
    if r.get("async"):
        ov = _ckpt_overlap_steps(r)
        s += f" [async, +{ov} step(s) to commit]"
    return s


def _ewma(values: list[float], alpha: float = 0.1):
    out = None
    for v in values:
        if v != v:  # NaN — a bad step's loss; skip, don't poison
            continue
        out = v if out is None else alpha * v + (1.0 - alpha) * out
    return out


def _health_section(records: list[dict]) -> list[str]:
    """Training-health section (ISSUE 12): loss trend/EWMA, grad-norm
    trend, update ratio, the per-merge-group grad-norm trend, and the
    postmortem bundle index."""
    from mgwfbp_tpu.telemetry import events_of

    lines: list[str] = []
    health = events_of(records, "health")
    if health:
        losses = [float(h.get("loss", float("nan"))) for h in health]
        norms = [float(h.get("grad_norm", float("nan"))) for h in health]
        ratios = [
            float(h.get("update_ratio", float("nan"))) for h in health
        ]
        finite_n = [v for v in norms if v == v]
        lines.append("")
        lines.append(f"training health ({len(health)} records):")
        lines.append(
            f"  loss: first {_fmt_s(losses[0])} -> last "
            f"{_fmt_s(losses[-1])} (ewma {_fmt_s(_ewma(losses))}); "
            f"update/param ratio last {_fmt_s(ratios[-1])}"
        )
        if finite_n:
            lines.append(
                f"  grad norm: first {_fmt_s(norms[0])} -> last "
                f"{_fmt_s(norms[-1])} (max {_fmt_s(max(finite_n))})"
            )
        bad = sum(1 for v in losses if v != v)
        if bad:
            lines.append(
                f"  non-finite loss records: {bad} (see bad_step rows)"
            )
        per_group = [h.get("group_norms") for h in health]
        per_group = [g for g in per_group if g]
        if per_group and len(per_group[-1]) == len(per_group[0]):
            lines.append(
                f"  {'group':>5} {'gnorm_first':>12} {'gnorm_last':>12}"
            )
            for gi in range(len(per_group[0])):
                lines.append(
                    f"  {gi:>5} {_fmt_s(per_group[0][gi]):>12} "
                    f"{_fmt_s(per_group[-1][gi]):>12}"
                )
        comp = [h.get("compression_error") for h in health]
        comp = [c for c in comp if c]
        if comp:
            lines.append(
                f"  compression error (worst group): first "
                f"{_fmt_s(max(comp[0]))} -> last {_fmt_s(max(comp[-1]))}"
            )
    pms = events_of(records, "postmortem")
    if pms:
        lines.append("")
        lines.append(f"postmortem bundles ({len(pms)}):")
        lines.append(f"  {'trigger':>15} {'step':>8}  path")
        for r in pms:
            lines.append(
                f"  {str(r.get('trigger')):>15} "
                f"{str(r.get('step', '-')):>8}  {r.get('path')}"
            )
    return lines


def _serving_section(records: list[dict]) -> list[str]:
    """Serving-plane section (ISSUE 19): hot-reload count + lag trend,
    request latency quantiles, queue depth trend, batch fill, and the
    shadow-eval loss against the training loss it shadows."""
    from mgwfbp_tpu.telemetry import events_of

    lines: list[str] = []
    reloads = events_of(records, "reload")
    stats = events_of(records, "serve_stats")
    shadows = events_of(records, "shadow_eval")
    if not (reloads or stats or shadows):
        return lines
    lines.append("")
    lines.append("serving:")
    if reloads:
        lags = [float(r.get("lag_s", 0.0)) for r in reloads]
        lines.append(
            f"  hot-reloads: {len(reloads)} (step "
            f"{reloads[0].get('step')} -> {reloads[-1].get('step')}), "
            f"reload lag mean {_fmt_s(sum(lags) / len(lags))} s, "
            f"max {_fmt_s(max(lags))} s"
        )
    if stats:
        last = stats[-1]
        lines.append(
            f"  requests: {last.get('requests')} total, latency p50 "
            f"{_fmt_s(last.get('latency_p50_s'))} s / p95 "
            f"{_fmt_s(last.get('latency_p95_s'))} s / p99 "
            f"{_fmt_s(last.get('latency_p99_s'))} s, batch fill "
            f"{_fmt_s(last.get('batch_fill'))}"
        )
        depths = [float(s.get("queue_depth", 0)) for s in stats]
        lines.append(
            f"  queue depth: first {_fmt_s(depths[0])} -> last "
            f"{_fmt_s(depths[-1])} (max {_fmt_s(max(depths))})"
        )
    if shadows:
        first, last = shadows[0], shadows[-1]
        line = (
            f"  shadow eval: {len(shadows)} scores, loss "
            f"{_fmt_s(first.get('loss'))} (step {first.get('step')}) -> "
            f"{_fmt_s(last.get('loss'))} (step {last.get('step')})"
        )
        if last.get("train_loss") is not None:
            delta = float(last["loss"]) - float(last["train_loss"])
            line += (
                f"; vs training loss {_fmt_s(last.get('train_loss'))} "
                f"(delta {delta:+.4g})"
            )
        lines.append(line)
    return lines


def _alarm_lines(alarms: list[dict]) -> list[str]:
    """Active-alarm table rows (live /status and /fleet/status share the
    same alarm dicts the aggregator keeps)."""
    lines = [
        f"  {'kind':>14} {'group/proc':>10} {'residual':>10} {'band':>8}"
    ]
    for a in alarms:
        if a.get("alarm") == "straggler" or "slow_process" in a:
            kind = "straggler"
            who = f"p{a.get('slow_process')}"
            residual = _fmt_s(a.get("excess_s"))
            band = "-"
        else:
            # drift alarms report `residual`, health alarms `value`
            kind = str(a.get("kind"))
            who = (
                str(a.get("group"))
                if int(a.get("group", -1)) >= 0 else "agg"
            )
            residual = _fmt_s(a.get("residual", a.get("value")))
            band = _fmt_s(a.get("band"))
        procs = a.get("processes")
        lines.append(
            f"  {kind:>14} {who:>10} {residual:>10} {band:>8}"
            + (f"  reported by {sorted(procs)}" if procs else "")
        )
    return lines


def format_live_report(status: dict, values: dict) -> str:
    """One process's live view, from its /status JSON + parsed /metrics
    (same sections as the post-hoc report, sourced from the running
    job)."""
    lines: list[str] = []
    run = status.get("run", {}) or {}
    desc = ", ".join(f"{k}={v}" for k, v in sorted(run.items()))
    lines.append(f"live /status ({desc})" if desc else "live /status")
    lines.append(
        f"health: {'ok' if status.get('healthy') else 'UNHEALTHY'}"
        + (
            f" — {status.get('health_reason')}"
            if not status.get("healthy") else ""
        )
        + f" (uptime {_fmt_s(status.get('uptime_s'))} s)"
    )
    lines.append("")
    lines.append(
        f"steps: {values.get('mgwfbp_steps_total', 0)} recorded, at step "
        f"{status.get('step')} epoch {status.get('epoch')}, mean "
        f"{_fmt_s(values.get('mgwfbp_step_seconds'))} s/step "
        "(rolling window)"
    )
    sched = status.get("schedule")
    if sched:
        lines.append(
            f"schedule: {sched.get('comm_op')} x "
            f"{sched.get('num_groups')} group(s) "
            f"({sched.get('policy_detail')})"
        )
    eff = status.get("overlap_efficiency")
    if eff is not None:
        lines.append(
            f"overlap efficiency: {float(eff):.4f} (hidden "
            f"{_fmt_s(values.get('mgwfbp_comm_hidden_seconds'))} s + "
            f"exposed {_fmt_s(values.get('mgwfbp_comm_exposed_seconds'))}"
            " s per step)"
        )
    health = status.get("health")
    if health:
        lines.append(
            f"training health (step {health.get('step')}): loss "
            f"{_fmt_s(health.get('loss'))}, grad norm "
            f"{_fmt_s(health.get('grad_norm'))}, update/param ratio "
            f"{_fmt_s(health.get('update_ratio'))}"
        )
        gn = health.get("group_norms") or []
        if gn:
            lines.append(
                "  per-group grad norms: "
                + ", ".join(
                    f"g{gi}={_fmt_s(v)}" for gi, v in enumerate(gn)
                )
            )
        comp = health.get("compression_error") or []
        if comp:
            lines.append(
                f"  compression error (worst group): {_fmt_s(max(comp))}"
            )
    serving = status.get("serving")
    if serving:
        lines.append("")
        lines.append(
            f"serving: step {serving.get('step')}, "
            f"{serving.get('reloads', 0)} hot-reload(s), reload lag "
            f"{_fmt_s(serving.get('reload_lag_s'))} s"
        )
        st = serving.get("stats") or {}
        if st:
            lines.append(
                f"  requests {st.get('requests', 0)}, queue depth "
                f"{st.get('queue_depth', 0)}, batch fill "
                f"{_fmt_s(st.get('batch_fill'))}, latency p50 "
                f"{_fmt_s(st.get('latency_p50_s'))} s / p95 "
                f"{_fmt_s(st.get('latency_p95_s'))} s / p99 "
                f"{_fmt_s(st.get('latency_p99_s'))} s"
            )
        sh = serving.get("shadow") or {}
        if sh:
            line = (
                f"  shadow eval (step {sh.get('step')}): loss "
                f"{_fmt_s(sh.get('loss'))}"
            )
            if sh.get("train_loss") is not None:
                line += f" vs training {_fmt_s(sh.get('train_loss'))}"
            lines.append(line)
    pm = status.get("postmortems") or {}
    if pm.get("total"):
        lines.append(
            f"postmortem bundles: {pm['total']} written"
        )
        for b in pm.get("recent", []):
            lines.append(
                f"  {b.get('trigger')} @ step {b.get('step')}: "
                f"{b.get('path')}"
            )
    alarms = status.get("active_alarms") or []
    lines.append("")
    if alarms:
        lines.append(f"active alarms ({len(alarms)}):")
        lines.extend(_alarm_lines(alarms))
    else:
        lines.append("active alarms: none")
    lines.append("")
    lines.append("lifecycle counters:")
    for key, label in (
        ("mgwfbp_checkpoints_total", "checkpoints"),
        ("mgwfbp_resizes_total", "resizes"),
        ("mgwfbp_bad_steps_total", "bad steps"),
        ("mgwfbp_rollbacks_total", "rollbacks"),
        ("mgwfbp_preempts_total", "preempts"),
        ("mgwfbp_resumes_total", "resumes"),
        ("mgwfbp_failures_total", "hard failures"),
        ("mgwfbp_heals_total", "heals"),
        ("mgwfbp_watchdog_stalls_total", "watchdog stalls"),
        ("mgwfbp_autotune_commits_total", "autotune commits"),
        ("mgwfbp_drift_alarms_total", "drift alarms"),
        ("mgwfbp_straggler_alarms_total", "straggler alarms"),
        ("mgwfbp_health_alarms_total", "health alarms"),
        ("mgwfbp_postmortems_total", "postmortem bundles"),
        ("mgwfbp_profile_windows_total", "profile windows"),
        ("mgwfbp_serve_requests_total", "predict requests"),
        ("mgwfbp_serve_reloads_total", "hot-reloads"),
        ("mgwfbp_shadow_evals_total", "shadow evals"),
    ):
        v = values.get(key, 0)
        if v:
            lines.append(f"  {label}: {v}")
    prof = status.get("profile") or {}
    if prof.get("state") not in (None, "idle"):
        lines.append("")
        lines.append(f"profile window: {prof.get('state')}")
        res = prof.get("result")
        if res:
            lines.append(
                f"  {res.get('steps')} step(s), attribution="
                f"{res.get('attribution')}"
                + (
                    ", " + ", ".join(
                        f"g{g['group']}={_fmt_s(g.get('device_s'))}s"
                        for g in res.get("groups", [])
                        if "device_s" in g
                    ) if res.get("groups") else ""
                )
            )
    return "\n".join(lines)


def format_fleet_report(doc: dict) -> str:
    """The supervisor fan-in's group view (/fleet/status)."""
    lines = [
        f"fleet /fleet/status: {doc.get('reachable', 0)} process(es) "
        f"reachable, {len(doc.get('unreachable') or [])} unreachable, "
        f"{'healthy' if doc.get('healthy') else 'UNHEALTHY'}"
    ]
    table = doc.get("straggler_table") or []
    if table:
        lines.append("")
        lines.append("live straggler table (mean-excess vs fastest):")
        lines.append(
            f"  {'proc':>5} {'step':>8} {'mean_step_s':>12} "
            f"{'excess_s':>10} {'excess_%':>9}"
        )
        for r in table:
            lines.append(
                f"  {r['process']:>5} {str(r.get('step', '-')):>8} "
                f"{_fmt_s(r['mean_step_s']):>12} "
                f"{_fmt_s(r.get('excess_s')):>10} "
                f"{r.get('excess_pct', 0.0):>8.1f}%"
            )
    slow = doc.get("slowest_process")
    if slow:
        lines.append(
            f"slowest: process {slow['process']} "
            f"(+{_fmt_s(slow['excess_s'])} s/step, "
            f"+{slow['excess_pct']:.1f}%)"
        )
    heal = doc.get("heal")
    if heal:
        lines.append("")
        state = "enabled" if heal.get("enabled") else "DISABLED (--no-heal)"
        lines.append(
            f"self-healing: {state}, liveness grace "
            f"{_fmt_s(heal.get('liveness_grace_s'))} s, budget "
            f"{heal.get('budget')} restart(s)/class"
        )
        restarts = heal.get("restarts") or {}
        if restarts:
            lines.append(
                "  heals so far: " + ", ".join(
                    f"{cls}={n}" for cls, n in sorted(restarts.items())
                )
            )
        pending = heal.get("pending_failure")
        if pending:
            lines.append(
                f"  PENDING FAILURE: {pending.get('class')} on "
                f"{pending.get('target')} (step {pending.get('step')}) "
                "— draining to heal"
            )
    serving = doc.get("serving")
    if serving:
        lines.append("")
        lines.append(
            f"serve replicas: {serving.get('alive', 0)}/"
            f"{serving.get('replicas', 0)} alive, restarts "
            f"{serving.get('restarts')} (budget "
            f"{serving.get('restart_budget')}/replica)"
        )
    alarms = doc.get("active_alarms") or []
    lines.append("")
    if alarms:
        lines.append(f"fleet active alarms ({len(alarms)}):")
        lines.extend(_alarm_lines(alarms))
    else:
        lines.append("fleet active alarms: none")
    pms = doc.get("postmortems") or []
    if pms:
        lines.append("")
        lines.append("fleet postmortem bundles:")
        for row in pms:
            lines.append(
                f"  p{row.get('process')}: {row.get('total')} bundle(s)"
            )
            for b in row.get("recent", []):
                lines.append(
                    f"    {b.get('trigger')} @ step {b.get('step')}: "
                    f"{b.get('path')}"
                )
    for u in doc.get("unreachable") or []:
        lines.append(
            f"UNREACHABLE: p{u.get('process')} at {u.get('target')} "
            f"({u.get('error')})"
        )
    return "\n".join(lines)


def _fetch(url: str, timeout_s: float = 5.0):
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            return resp.status, resp.read().decode()
    except Exception as e:  # noqa: BLE001 — refused/timeout: try the
        # other endpoint family, then report
        return None, str(e)


def live_report(base: str) -> int:
    """Render from a RUNNING job: per-process /status + /metrics, or a
    supervisor fan-in's /fleet/status."""
    from mgwfbp_tpu.telemetry.export import parse_metrics_text

    base = base.rstrip("/")
    if not base.startswith("http"):
        base = "http://" + base
    code, body = _fetch(base + "/status")
    if code == 200:
        status = json.loads(body)
        mcode, mtext = _fetch(base + "/metrics")
        values = parse_metrics_text(mtext) if mcode == 200 else {}
        print(format_live_report(status, values))
        return 0
    fcode, fbody = _fetch(base + "/fleet/status")
    if fcode == 200:
        print(format_fleet_report(json.loads(fbody)))
        return 0
    print(
        f"telemetry_report: no live endpoint at {base} "
        f"(/status: {code or body}; /fleet/status: {fcode or fbody})",
        file=sys.stderr,
    )
    return 2


def _synthetic_stream(path: str) -> None:
    """Write a small but complete stream: header, steps, an overlap
    snapshot with a known hidden/exposed split, and lifecycle events."""
    from mgwfbp_tpu.telemetry import EventWriter, attribute_overlap

    w = EventWriter(path, run={"model": "selftest", "world": 8})
    tb = [0.010, 0.010, 0.010]
    groups = [(0, 1), (2,)]
    comm = [0.015, 0.010]
    nbytes = [1 << 20, 1 << 19]
    rows = attribute_overlap(groups, tb, comm, nbytes)
    step_s = 0.045
    for i in range(24):
        w.emit("step", step=i, epoch=0, start_s=i * step_s, dur_s=step_s)
    hidden = sum(r.hidden_s for r in rows)
    total = sum(r.comm_s for r in rows)
    w.emit(
        "overlap", step=23, epoch=0, step_s=step_s,
        tb_total_s=sum(tb), comm_s=total, hidden_s=hidden,
        exposed_s=total - hidden,
        efficiency=hidden / total, attribution="cost-model",
        timeline_end_s=max(sum(tb), max(r.start_s + r.comm_s for r in rows)),
    )
    for r in rows:
        w.emit(
            "comm_group", step=23, group=r.group, nbytes=r.nbytes,
            comm_s=r.comm_s, start_s=r.start_s, hidden_s=r.hidden_s,
            exposed_s=r.exposed_s, attribution="cost-model",
        )
    w.emit("resize", old_world=8, new_world=4,
           schedule_source="schedule-cache", num_groups=2)
    w.emit("checkpoint", epoch=0, iteration=24, mid_epoch=False)
    # async shard-native saves (ISSUE 16): one committed at the next
    # cadence step, one whose payload write overlapped three steps
    w.emit("checkpoint", epoch=0, iteration=8, mid_epoch=True,
           epoch_step=8, duration_s=0.030, bytes=1 << 20,
           format="sharded", commit_iteration=9, **{"async": True})
    w.emit("checkpoint", epoch=0, iteration=16, mid_epoch=True,
           epoch_step=16, duration_s=0.140, bytes=1 << 20,
           format="sharded", commit_iteration=19, **{"async": True})
    w.emit("drift_alarm", kind="comm_residual", step=20, residual=4.5,
           band=3.0, active=True, group=1)
    w.emit("drift_alarm", kind="comm_residual", step=23, residual=1.2,
           band=3.0, active=False, group=1)
    w.emit("straggler", step=22, slow_process=1, excess_s=0.013,
           step_s_max=0.058, step_s_min=0.045, active=True)
    # training-health stream + alarm + postmortem (ISSUE 12)
    for i in range(24):
        w.emit(
            "health", step=i, epoch=0,
            loss=2.0 - 0.05 * i if i != 20 else 9.0,
            grad_norm=1.0 + (8.0 if i == 20 else 0.0),
            update_ratio=1e-3,
            group_norms=[0.8, 0.6],
            compression_error=[0.02, 0.03],
        )
    w.emit("health_alarm", kind="loss_spike", step=20, value=5.2,
           band=2.0, active=True, group=-1)
    w.emit("health_alarm", kind="loss_spike", step=22, value=1.1,
           band=2.0, active=False, group=-1)
    w.emit("postmortem", trigger="health_alarm", step=20,
           path="/tmp/run/postmortems/0000")
    # serving plane (ISSUE 19): hot-reloads, request stats, shadow evals
    w.emit("reload", step=8, lag_s=0.4, duration_s=0.05)
    w.emit("reload", step=16, lag_s=0.6, duration_s=0.04)
    w.emit("serve_stats", requests=10, queue_depth=1, batch_fill=0.5,
           latency_p50_s=0.02, latency_p95_s=0.04, latency_p99_s=0.05)
    w.emit("serve_stats", requests=24, queue_depth=0, batch_fill=0.75,
           latency_p50_s=0.018, latency_p95_s=0.035, latency_p99_s=0.04)
    w.emit("shadow_eval", step=8, loss=1.9, train_loss=1.8)
    w.emit("shadow_eval", step=16, loss=1.4, train_loss=1.35)
    # self-healing supervisor (ISSUE 20): a hard-failure verdict and the
    # healing action taken, as the supervisor's own stream records them
    w.emit("failure", **{"class": "oom_kill"}, target="p1", rc=-9,
           step=20)
    w.emit("heal", action="shrink", **{"class": "oom_kill"}, target="p1",
           old_world=2, world=1, restarts=1)
    w.emit("failure", **{"class": "wedged"}, target="p0,p1", step=21)
    w.emit("heal", action="relaunch", **{"class": "wedged"},
           target="p0,p1", world=2, restarts=1)
    w.close()


def selftest() -> int:
    """Writer -> reader -> report -> exports round trip on synthetic data."""
    from mgwfbp_tpu.telemetry import read_events
    from mgwfbp_tpu.telemetry.export import (
        write_chrome_trace, write_prometheus,
    )

    with tempfile.TemporaryDirectory(prefix="mgwfbp_tel_selftest_") as d:
        path = os.path.join(d, "telemetry.jsonl")
        _synthetic_stream(path)
        records = read_events(path)
        report = format_report(records)
        assert "overlap efficiency" in report, report
        assert "alarms:" in report and "straggler" in report, report
        # ISSUE 12: training-health section, health alarm row, and the
        # postmortem index table all render from the same stream
        assert "training health (24 records)" in report, report
        assert "loss_spike" in report, report
        assert "postmortem bundles (1):" in report, report
        assert "/tmp/run/postmortems/0000" in report, report
        assert "gnorm_first" in report, report
        # ISSUE 16: the save-duration trend section renders, async saves
        # are marked in the lifecycle, and the save whose payload write
        # spanned >1 step is flagged with its commit iteration
        assert "checkpoint saves (2, 2 async):" in report, report
        assert "[async, +1 step(s) to commit]" in report, report
        assert (
            "save at iter 16 overlapped 3 steps (committed at iter 19)"
            in report
        ), report
        assert "save at iter 8 overlapped" not in report, report
        # ISSUE 19: the serving section renders latency quantiles, queue
        # depth trend, batch fill, reload lag, shadow-vs-training loss
        assert "serving:" in report, report
        assert "hot-reloads: 2 (step 8 -> 16)" in report, report
        assert "latency p50 0.018 s / p95 0.035 s / p99 0.04 s" in report
        assert "queue depth: first 1 -> last 0" in report, report
        assert "shadow eval: 2 scores" in report, report
        assert "vs training loss 1.35 (delta +0.05)" in report, report
        # ISSUE 20: failure verdicts and healing actions render in the
        # lifecycle section
        assert "FAILURE [oom_kill] on p1 rc -9 at step 20" in report
        assert (
            "HEAL shrink [oom_kill] 2 -> 1 proc(s) (1 restart(s))"
            in report
        ), report
        assert "FAILURE [wedged] on p0,p1 at step 21" in report, report
        assert (
            "HEAL relaunch [wedged] at world 2 (1 restart(s))" in report
        ), report
        trace_path = os.path.join(d, "trace.json")
        doc = write_chrome_trace(trace_path, records)
        with open(trace_path) as f:
            loaded = json.load(f)
        assert loaded["traceEvents"] and doc["traceEvents"]
        prom = write_prometheus(os.path.join(d, "metrics.prom"), records)
        assert "mgwfbp_steps_total 24" in prom, prom
        assert "mgwfbp_overlap_efficiency" in prom
        # the file dump and the live /metrics endpoint share ONE registry
        # + aggregator; serving the replayed stream must render the very
        # same text (ISSUE 9: the two surfaces cannot diverge)
        from mgwfbp_tpu.telemetry.export import render_metrics
        from mgwfbp_tpu.telemetry.serve import MetricsAggregator

        agg = MetricsAggregator()
        agg.replay(records)
        assert render_metrics(agg.values()) == prom
        assert "mgwfbp_drift_alarms_total 1" in prom, prom
        assert "mgwfbp_health_alarms_total 1" in prom, prom
        assert "mgwfbp_postmortems_total 1" in prom, prom
        assert "mgwfbp_health_grad_norm" in prom, prom
        assert "mgwfbp_serve_reloads_total 2" in prom, prom
        assert "mgwfbp_shadow_evals_total 2" in prom, prom
        assert "mgwfbp_serve_step 16" in prom, prom
        assert "mgwfbp_serve_latency_p95_seconds 0.035" in prom, prom
        assert "mgwfbp_shadow_eval_delta 0.05" in prom, prom
        assert "mgwfbp_failures_total 2" in prom, prom
        assert "mgwfbp_heals_total 2" in prom, prom
        # --live round trip: serve the replayed aggregator over HTTP and
        # render the live report from /status + /metrics; then fan two
        # such children into a fleet view (ISSUE 10) and render that
        from mgwfbp_tpu.telemetry.export import parse_metrics_text
        from mgwfbp_tpu.telemetry.fleet import FleetServer, scrape_fleet
        from mgwfbp_tpu.telemetry.serve import TelemetryServer

        srv = TelemetryServer(agg, 0, host="127.0.0.1")
        fleet = FleetServer(
            lambda: {0: ("127.0.0.1", srv.port),
                     1: ("127.0.0.1", srv.port)},
            port=0,
            # the supervisor's heal/serving state flows through the
            # fan-in meta verbatim (ISSUE 20)
            meta_provider=lambda: {
                "heal": {
                    "enabled": True, "restarts": {"oom_kill": 1},
                    "budget": 2, "liveness_grace_s": 120.0,
                },
                "serving": {
                    "replicas": 2, "alive": 1, "restarts": [0, 2],
                    "restart_budget": 3,
                },
            },
        )
        try:
            code, body = _fetch(f"http://127.0.0.1:{srv.port}/status")
            assert code == 200, body
            status = json.loads(body)
            code, mtext = _fetch(f"http://127.0.0.1:{srv.port}/metrics")
            assert code == 200 and parse_metrics_text(mtext), mtext
            live = format_live_report(status, parse_metrics_text(mtext))
            assert "steps: 24 recorded" in live, live
            # the --live view carries the same serving section, sourced
            # from /status's `serving` document
            assert "serving: step 16, 2 hot-reload(s)" in live, live
            assert "shadow eval (step 16)" in live, live
            # ISSUE 20: failure/heal lifecycle counters in the live view
            assert "hard failures: 2" in live, live
            assert "heals: 2" in live, live
            children = scrape_fleet(
                {0: ("127.0.0.1", srv.port), 1: ("127.0.0.1", srv.port)}
            )
            assert all(c.reachable for c in children)
            code, fbody = _fetch(
                f"http://127.0.0.1:{fleet.port}/fleet/status"
            )
            assert code == 200, fbody
            fdoc = json.loads(fbody)
            assert {r["process"] for r in fdoc["straggler_table"]} == {
                0, 1,
            }, fdoc
            code, fmet = _fetch(
                f"http://127.0.0.1:{fleet.port}/fleet/metrics"
            )
            assert 'mgwfbp_steps_total{process="0"} 24' in fmet, fmet
            assert 'mgwfbp_steps_total{process="1"} 24' in fmet, fmet
            # ISSUE 20: the supervisor's heal + serve-replica state
            # renders in the fleet view
            freport = format_fleet_report(fdoc)
            assert "self-healing: enabled" in freport, freport
            assert "heals so far: oom_kill=1" in freport, freport
            assert (
                "serve replicas: 1/2 alive, restarts [0, 2] "
                "(budget 3/replica)" in freport
            ), freport
            print(format_fleet_report(fdoc))
            print()
        finally:
            fleet.close()
            srv.close()
        print(report)
        print()
        print(
            f"telemetry selftest OK: {len(records)} records, "
            f"{len(loaded['traceEvents'])} trace events"
        )
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="telemetry_report",
        description="Render a run's telemetry event stream: overlap table, "
        "step trend, lifecycle; optional Chrome-trace/Prometheus export",
    )
    p.add_argument("events", nargs="?",
                   help="telemetry JSONL path, or a run dir containing "
                   "telemetry.jsonl")
    p.add_argument("--chrome-trace", dest="chrome_trace", default=None,
                   help="write a chrome://tracing / Perfetto JSON here")
    p.add_argument("--prometheus", default=None,
                   help="write a Prometheus text-exposition dump here")
    p.add_argument("--live", default=None, metavar="URL",
                   help="render from a RUNNING job's /status + /metrics "
                        "(or a supervisor fan-in's /fleet/status) "
                        "instead of JSONL files, e.g. "
                        "http://127.0.0.1:9100")
    p.add_argument("--selftest", action="store_true",
                   help="run the synthetic end-to-end smoke and exit")
    args = p.parse_args(argv)

    if args.selftest:
        return selftest()
    if args.live:
        return live_report(args.live)
    if not args.events:
        p.error("events path required (or --selftest, or --live URL)")
    path = args.events
    if os.path.isdir(path):
        path = os.path.join(path, "telemetry.jsonl")

    # read_event_set handles size-rotated streams (telemetry.jsonl.0000,
    # .0001, ... + the active file) as one continuous timeline; a bare
    # un-rotated file reads identically
    from mgwfbp_tpu.telemetry import read_event_set

    try:
        records = read_event_set(path)
    except FileNotFoundError:
        print(f"telemetry_report: no events file at {path}", file=sys.stderr)
        return 2
    print(format_report(records))
    if args.chrome_trace:
        from mgwfbp_tpu.telemetry.export import write_chrome_trace

        doc = write_chrome_trace(args.chrome_trace, records)
        print(f"chrome trace: {args.chrome_trace} "
              f"({len(doc['traceEvents'])} events; open in Perfetto)")
    if args.prometheus:
        from mgwfbp_tpu.telemetry.export import write_prometheus

        write_prometheus(args.prometheus, records)
        print(f"prometheus dump: {args.prometheus}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
