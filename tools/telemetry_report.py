"""Telemetry-stream report: overlap table + run trend from one events file.

Reads a run's telemetry JSONL (written by `mgwfbp_tpu.telemetry`, enabled
with ``--telemetry`` on the train CLI) and prints:

  * the run header (model/world/comm_op/policy);
  * the step-time trend — span count, mean/min/max seconds per step, first
    vs last 10-span window (throughput drift over the run);
  * the per-merge-group exposed/hidden comm table from the latest overlap
    snapshot, with the attribution source (``trace`` on backends whose op
    metadata keeps the `mgwfbp_groupNNNN` scopes; ``cost-model`` on the
    CPU mesh, whose traces drop the name stack);
  * the aggregate overlap-efficiency number (hidden / total comm — the
    paper's headline metric);
  * the alarms table — cost-model drift rows (kind, merge group, residual
    vs band) and live straggler rows (slow process, excess) from the
    drift detector / multi-host probe (telemetry/drift.py), raise and
    clear edges both;
  * lifecycle events: resizes (and which schedule path won), checkpoints,
    autotune race rows, watchdog stalls, bench skips.

Optionally renders the same stream for external viewers:

  python tools/telemetry_report.py <run>/telemetry.jsonl
  python tools/telemetry_report.py <run>/telemetry.jsonl \
      --chrome-trace trace.json --prometheus metrics.prom
  python tools/telemetry_report.py --selftest   # synthetic stream smoke

``--selftest`` exercises the full pipeline (writer -> reader -> report ->
Chrome trace -> Prometheus) on a synthetic stream in a temp dir — the
standing-gate smoke tools/check.sh runs, no accelerator or dataset needed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fmt_s(v) -> str:
    return f"{v:.6g}" if isinstance(v, (int, float)) else "n/a"


def _window_mean(spans: list[dict], sl: slice) -> float:
    w = spans[sl]
    return sum(float(s["dur_s"]) for s in w) / max(len(w), 1)


def format_report(records: list[dict]) -> str:
    from mgwfbp_tpu.telemetry import events_of

    lines: list[str] = []
    header = next(iter(events_of(records, "header")), {})
    run = header.get("run", {}) or {}
    desc = ", ".join(f"{k}={v}" for k, v in sorted(run.items()))
    lines.append(
        f"telemetry stream: schema v{header.get('schema_version', '?')}"
        + (f" ({desc})" if desc else "")
    )

    steps = events_of(records, "step")
    if steps:
        durs = [float(s["dur_s"]) for s in steps]
        lines.append("")
        lines.append(
            f"steps: {len(steps)} spans, mean {_fmt_s(sum(durs)/len(durs))} "
            f"s/step (min {_fmt_s(min(durs))}, max {_fmt_s(max(durs))})"
        )
        if len(steps) >= 20:
            first = _window_mean(steps, slice(0, 10))
            last = _window_mean(steps, slice(-10, None))
            drift = (last - first) / first * 100.0 if first > 0 else 0.0
            lines.append(
                f"trend: first-10 {_fmt_s(first)} s -> last-10 "
                f"{_fmt_s(last)} s ({drift:+.1f}%)"
            )
    else:
        lines.append("steps: none recorded")

    from mgwfbp_tpu.telemetry.export import latest_snapshot

    snap, rows = latest_snapshot(records)
    if snap is not None:
        lines.append("")
        lines.append(
            f"overlap snapshot (step {snap.get('step')}, attribution="
            f"{snap.get('attribution')}):"
        )
        cross = float(snap.get("tf_total_s", 0.0) or 0.0) > 0.0
        lines.append(
            f"  {'group':>5} {'bytes':>12} {'comm_s':>10} {'hidden_s':>10} "
            f"{'exposed_s':>10}"
            + (f" {'ag_s':>10}" if cross else "")
        )
        for r in rows:
            row = (
                f"  {int(r['group']):>5} {int(r['nbytes']):>12} "
                f"{_fmt_s(r['comm_s']):>10} {_fmt_s(r['hidden_s']):>10} "
                f"{_fmt_s(r['exposed_s']):>10}"
            )
            if cross:
                # cross-step regime: ag_s is the deferred all-gather leg
                # riding the NEXT step's forward
                row += f" {_fmt_s(r.get('ag_s', 0.0)):>10}"
            lines.append(row)
        tail = (
            f"(forward {_fmt_s(snap.get('tf_total_s'))} s, backward "
            if cross
            else "(backward "
        )
        lines.append(
            f"  total comm {_fmt_s(snap.get('comm_s'))} s = hidden "
            f"{_fmt_s(snap.get('hidden_s'))} s + exposed "
            f"{_fmt_s(snap.get('exposed_s'))} s "
            + tail
            + f"{_fmt_s(snap.get('tb_total_s'))} s, step "
            f"{_fmt_s(snap.get('step_s'))} s)"
        )
        if cross:
            lines.append(
                "  cross-step regime (rs_fwd_ag): each group's AG is "
                "deferred into the next step's forward; hidden counts "
                "both forward- and backward-side overlap"
            )
        lines.append(
            f"overlap efficiency: {float(snap.get('efficiency', 0.0)):.4f} "
            "(hidden / total comm; 1.0 = fully hidden)"
        )
    else:
        lines.append("")
        lines.append("overlap: no snapshot recorded (single-device run, "
                     "policy 'none', or telemetry off during fit)")

    alarms = events_of(records, "drift_alarm", "straggler")
    if alarms:
        lines.append("")
        lines.append("alarms:")
        lines.append(
            f"  {'kind':>14} {'edge':>6} {'group/proc':>10} "
            f"{'residual':>10} {'band':>8} {'step':>8}"
        )
        for r in alarms:
            if r.get("event") == "drift_alarm":
                kind = str(r.get("kind"))
                who = (
                    str(r.get("group"))
                    if int(r.get("group", -1)) >= 0 else "agg"
                )
                residual = _fmt_s(r.get("residual"))
                band = _fmt_s(r.get("band"))
            else:
                kind = "straggler"
                who = f"p{r.get('slow_process')}"
                residual = _fmt_s(r.get("excess_s"))
                band = "-"
            lines.append(
                f"  {kind:>14} "
                f"{'RAISE' if r.get('active') else 'clear':>6} "
                f"{who:>10} {residual:>10} {band:>8} "
                f"{str(r.get('step', '-')):>8}"
            )

    lifecycle = []
    for ev, render in (
        ("resize", lambda r: (
            f"resize {r.get('old_world')} -> {r.get('new_world')} "
            f"({r.get('schedule_source')}, {r.get('num_groups')} groups)")),
        ("checkpoint", lambda r: (
            f"checkpoint epoch {r.get('epoch')} iter {r.get('iteration')}")),
        ("autotune_race", lambda r: (
            f"autotune race {r.get('label')}: "
            f"{_fmt_s(r.get('measured_step_s'))} s/step "
            f"({'verified' if r.get('verified') else 'rejected'})")),
        ("autotune_commit", lambda r: (
            f"autotune commit {r.get('winner')} "
            f"({r.get('comm_op')}, {r.get('num_groups')} groups, "
            f"source={r.get('source')})")),
        ("watchdog_stall", lambda r: (
            f"WATCHDOG STALL in {r.get('phase')!r} after "
            f"{_fmt_s(r.get('idle_s'))} s"
            + (" (aborted)" if r.get("abort") else ""))),
        ("bench_skip", lambda r: f"bench skipped: {r.get('detail')}"),
        ("bad_step", lambda r: (
            f"BAD STEP {r.get('step')} (epoch {r.get('epoch')}): "
            f"{_fmt_s(r.get('nonfinite'))} non-finite gradient element(s), "
            "update dropped")),
        ("rollback", lambda r: (
            f"ROLLBACK after {r.get('bad_steps')} consecutive bad steps "
            f"-> restored iter {r.get('restored_iteration')} "
            f"(epoch {r.get('restored_epoch')})")),
        ("preempt", lambda r: (
            f"PREEMPTED by {r.get('signal')} at epoch {r.get('epoch')} "
            f"iter {r.get('iteration')} (checkpointed, rc 75)")),
        ("resume", lambda r: (
            f"resumed at epoch {r.get('epoch')} iter {r.get('iteration')}"
            + (" (mid-epoch)" if r.get("mid_epoch") else " (boundary)"))),
    ):
        for r in events_of(records, ev):
            lifecycle.append(render(r))
    if lifecycle:
        lines.append("")
        lines.append("lifecycle:")
        lines.extend(f"  {s}" for s in lifecycle)
    return "\n".join(lines)


def _synthetic_stream(path: str) -> None:
    """Write a small but complete stream: header, steps, an overlap
    snapshot with a known hidden/exposed split, and lifecycle events."""
    from mgwfbp_tpu.telemetry import EventWriter, attribute_overlap

    w = EventWriter(path, run={"model": "selftest", "world": 8})
    tb = [0.010, 0.010, 0.010]
    groups = [(0, 1), (2,)]
    comm = [0.015, 0.010]
    nbytes = [1 << 20, 1 << 19]
    rows = attribute_overlap(groups, tb, comm, nbytes)
    step_s = 0.045
    for i in range(24):
        w.emit("step", step=i, epoch=0, start_s=i * step_s, dur_s=step_s)
    hidden = sum(r.hidden_s for r in rows)
    total = sum(r.comm_s for r in rows)
    w.emit(
        "overlap", step=23, epoch=0, step_s=step_s,
        tb_total_s=sum(tb), comm_s=total, hidden_s=hidden,
        exposed_s=total - hidden,
        efficiency=hidden / total, attribution="cost-model",
        timeline_end_s=max(sum(tb), max(r.start_s + r.comm_s for r in rows)),
    )
    for r in rows:
        w.emit(
            "comm_group", step=23, group=r.group, nbytes=r.nbytes,
            comm_s=r.comm_s, start_s=r.start_s, hidden_s=r.hidden_s,
            exposed_s=r.exposed_s, attribution="cost-model",
        )
    w.emit("resize", old_world=8, new_world=4,
           schedule_source="schedule-cache", num_groups=2)
    w.emit("checkpoint", epoch=0, iteration=24, mid_epoch=False)
    w.emit("drift_alarm", kind="comm_residual", step=20, residual=4.5,
           band=3.0, active=True, group=1)
    w.emit("drift_alarm", kind="comm_residual", step=23, residual=1.2,
           band=3.0, active=False, group=1)
    w.emit("straggler", step=22, slow_process=1, excess_s=0.013,
           step_s_max=0.058, step_s_min=0.045, active=True)
    w.close()


def selftest() -> int:
    """Writer -> reader -> report -> exports round trip on synthetic data."""
    from mgwfbp_tpu.telemetry import read_events
    from mgwfbp_tpu.telemetry.export import (
        write_chrome_trace, write_prometheus,
    )

    with tempfile.TemporaryDirectory(prefix="mgwfbp_tel_selftest_") as d:
        path = os.path.join(d, "telemetry.jsonl")
        _synthetic_stream(path)
        records = read_events(path)
        report = format_report(records)
        assert "overlap efficiency" in report, report
        assert "alarms:" in report and "straggler" in report, report
        trace_path = os.path.join(d, "trace.json")
        doc = write_chrome_trace(trace_path, records)
        with open(trace_path) as f:
            loaded = json.load(f)
        assert loaded["traceEvents"] and doc["traceEvents"]
        prom = write_prometheus(os.path.join(d, "metrics.prom"), records)
        assert "mgwfbp_steps_total 24" in prom, prom
        assert "mgwfbp_overlap_efficiency" in prom
        # the file dump and the live /metrics endpoint share ONE registry
        # + aggregator; serving the replayed stream must render the very
        # same text (ISSUE 9: the two surfaces cannot diverge)
        from mgwfbp_tpu.telemetry.export import render_metrics
        from mgwfbp_tpu.telemetry.serve import MetricsAggregator

        agg = MetricsAggregator()
        agg.replay(records)
        assert render_metrics(agg.values()) == prom
        assert "mgwfbp_drift_alarms_total 1" in prom, prom
        print(report)
        print()
        print(
            f"telemetry selftest OK: {len(records)} records, "
            f"{len(loaded['traceEvents'])} trace events"
        )
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="telemetry_report",
        description="Render a run's telemetry event stream: overlap table, "
        "step trend, lifecycle; optional Chrome-trace/Prometheus export",
    )
    p.add_argument("events", nargs="?",
                   help="telemetry JSONL path, or a run dir containing "
                   "telemetry.jsonl")
    p.add_argument("--chrome-trace", dest="chrome_trace", default=None,
                   help="write a chrome://tracing / Perfetto JSON here")
    p.add_argument("--prometheus", default=None,
                   help="write a Prometheus text-exposition dump here")
    p.add_argument("--selftest", action="store_true",
                   help="run the synthetic end-to-end smoke and exit")
    args = p.parse_args(argv)

    if args.selftest:
        return selftest()
    if not args.events:
        p.error("events path required (or --selftest)")
    path = args.events
    if os.path.isdir(path):
        path = os.path.join(path, "telemetry.jsonl")

    # read_event_set handles size-rotated streams (telemetry.jsonl.0000,
    # .0001, ... + the active file) as one continuous timeline; a bare
    # un-rotated file reads identically
    from mgwfbp_tpu.telemetry import read_event_set

    try:
        records = read_event_set(path)
    except FileNotFoundError:
        print(f"telemetry_report: no events file at {path}", file=sys.stderr)
        return 2
    print(format_report(records))
    if args.chrome_trace:
        from mgwfbp_tpu.telemetry.export import write_chrome_trace

        doc = write_chrome_trace(args.chrome_trace, records)
        print(f"chrome trace: {args.chrome_trace} "
              f"({len(doc['traceEvents'])} events; open in Perfetto)")
    if args.prometheus:
        from mgwfbp_tpu.telemetry.export import write_prometheus

        write_prometheus(args.prometheus, records)
        print(f"prometheus dump: {args.prometheus}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
