"""Gamma sensitivity of the auto-policy argmin (VERDICT r4 #7).

gamma (per-collective pack/dispatch overhead) is the worst-calibrated term
in the cost model — held-out interpolation error at P=4 was 26.8% vs 7.5%
for beta (profiles/family_interp_check.json) — and it both gates the scan's
merge rule (c) and scales linearly with group count in every simulation.
This tool quantifies what that residual error does to the DECISION: for
each grid model it re-runs the auto argmin with gamma scaled x{0.7, 1.0,
1.3} (the held-out error band) and reports whether the chosen schedule
flips, and what the flip costs under the unscaled model.

A flip with near-zero regret means the argmin sits on a plateau (two
schedules within noise of each other) — harmless. A flip with material
regret would mean gamma calibration quality limits auto's wins.

Usage:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python tools/gamma_sensitivity.py --models resnet20,resnet56,vgg16 \
    --comm-profile profiles/cpu_family.json --out profiles/gamma_sensitivity.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SCALES = (0.7, 1.0, 1.3)


def analyze_model(model_name, batch, comm_profile, scales=SCALES):
    import jax
    import jax.numpy as jnp

    from overlap_report import measure_tb

    from mgwfbp_tpu import models as zoo
    from mgwfbp_tpu.optim import make_optimizer
    from mgwfbp_tpu.parallel.allreduce import arrival_order
    from mgwfbp_tpu.parallel.costmodel import load_profile, resolve_profile
    from mgwfbp_tpu.parallel.solver import auto_groups, simulate_groups
    from mgwfbp_tpu.train import create_train_state

    n_dev = len(jax.devices())
    model, meta = zoo.create_model(model_name)
    tx, _ = make_optimizer(
        0.1, momentum=0.9, weight_decay=1e-4, lr_schedule="const",
        dataset=meta.dataset, num_batches_per_epoch=1,
    )
    state = create_train_state(
        jax.random.PRNGKey(0), model,
        jnp.zeros((1,) + tuple(meta.input_shape), meta.input_dtype), tx,
    )
    tb = measure_tb(model, meta, state.params, state.batch_stats, batch)
    leaves = jax.tree_util.tree_leaves(state.params)
    paths = jax.tree_util.tree_flatten_with_path(state.params)[0]
    names = [jax.tree_util.keystr(kp) for kp, _ in paths]
    perm = arrival_order(len(names), names=names)
    sizes = [int(leaves[i].size) for i in perm]
    itemsizes = [int(leaves[i].dtype.itemsize) for i in perm]
    nbytes = [s * it for s, it in zip(sizes, itemsizes)]

    cost = resolve_profile(load_profile(comm_profile), max(n_dev, 2))
    gamma0 = float(getattr(cost, "gamma", 0.0))
    overlap = float(getattr(cost, "overlap", 1.0))
    pack_beta = float(getattr(cost, "pack_beta", 0.0))

    rows = {}
    choices = {}
    for s in scales:
        g = gamma0 * s
        groups, detail = auto_groups(
            sizes, tb, alpha=cost.alpha, cost=cost.predict,
            itemsize=itemsizes, gamma=g, overlap=overlap,
            pack_beta=pack_beta,
        )
        # regret: how much worse this choice is than the unscaled-model
        # optimum, PRICED UNDER THE UNSCALED MODEL (if the true gamma is
        # gamma0 but we calibrated gamma0*s, we pick `groups` and pay this)
        t_at_nominal, _, _ = simulate_groups(
            groups, nbytes, tb, cost.predict, gamma0, overlap, pack_beta
        )
        rows[str(s)] = {
            "gamma": g,
            "chosen": detail,
            "num_groups": len(groups),
            "group_shape_hash": hash(tuple(map(tuple, groups))) & 0xFFFFFFFF,
            "time_under_nominal_gamma_s": round(t_at_nominal, 6),
            "_groups": groups,
        }
        choices[str(s)] = tuple(map(tuple, groups))
    nominal = rows["1.0"]
    t_opt = nominal["time_under_nominal_gamma_s"]
    for s in scales:
        r = rows[str(s)]
        r["regret_vs_nominal_s"] = round(
            r["time_under_nominal_gamma_s"] - t_opt, 6
        )
        r["regret_frac"] = round(
            (r["time_under_nominal_gamma_s"] - t_opt) / max(t_opt, 1e-12), 5
        )
        del r["_groups"]
    flips = sorted(
        {s for s in map(str, scales) if choices[s] != choices["1.0"]}
    )
    return {
        "model": model_name,
        "batch_per_device": batch,
        "n_devices": n_dev,
        "gamma_nominal": gamma0,
        "overlap": overlap,
        "pack_beta": pack_beta,
        "tb_total_s": round(sum(tb), 6),
        "by_scale": rows,
        "schedule_flips_at": flips,
        "max_regret_frac": max(r["regret_frac"] for r in rows.values()),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--models", default="resnet20,resnet56,vgg16")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--comm-profile", dest="comm_profile",
                    default="profiles/cpu_family.json")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    from mgwfbp_tpu.utils.platform import apply_platform_overrides

    apply_platform_overrides()
    models = [m.strip() for m in args.models.split(",") if m.strip()]
    per_model = {m: analyze_model(m, args.batch, args.comm_profile)
                 for m in models}
    worst = max(r["max_regret_frac"] for r in per_model.values())
    report = {
        "what": (
            "auto-policy argmin re-run with gamma x{0.7,1.0,1.3} (the "
            "held-out calibration error band, family_interp_check.json); "
            "a 'flip' is a different chosen schedule, its regret is the "
            "extra time paid under the NOMINAL gamma"
        ),
        "scales": list(SCALES),
        "comm_profile": args.comm_profile,
        "models": per_model,
        "conclusion": {
            "max_regret_frac_any_model_any_scale": worst,
            "gamma_error_band_is_decision_safe": bool(worst < 0.02),
        },
    }
    text = json.dumps(report, indent=2)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            f.write(text)
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
