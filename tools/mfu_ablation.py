"""MFU roofline counterfactuals for resnet50 (VERDICT r4 #6).

The r4 roofline artifact (profiles/mfu_roofline_resnet50_tpu.json) argued
MFU 0.30 is HBM-bound from bandwidth accounting alone; this tool turns the
irreducibility claim empirical by MEASURING the counterfactual rows it only
reasoned about:

  * batch 64 / 128 / 256 — per-sample HBM traffic is ~batch-invariant, so
    throughput should be flat if the HBM diagnosis is right (the r3 sweep
    saw this; re-measured here on the current code);
  * uint8 input + on-device normalize — cuts the input-read traffic 4x
    (and models the H2D-lean production input path);
  * bf16 batch statistics (MGWFBP_BN_DTYPE=bfloat16) — runs the BN
    reduce/broadcast passes in bf16, the ~5.5%-of-device-time 'reduce'
    category in the r4 per-category table.

Each row: bench-protocol timing (AOT-compiled donated step, >=30 timed
iters, ONE host sync after the last chained step) + XLA cost analysis
(flops, bytes_accessed). Writes an "ablations" section into the roofline
artifact (v2).

Run on the TPU chip (no platform override):  python tools/mfu_ablation.py
CPU smoke:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=1 \
    python tools/mfu_ablation.py --iters 3 --no-save
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ARTIFACT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "profiles", "mfu_roofline_resnet50_tpu.json",
)


def _build(batch, uint8_input=False):
    """Bench-protocol setup for one row: returns (timed_fn, state, batch,
    flops, bytes_accessed)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from flax import linen as nn

    from mgwfbp_tpu import models as zoo
    from mgwfbp_tpu.optim import make_optimizer
    from mgwfbp_tpu.parallel.mesh import MeshSpec, make_mesh
    from mgwfbp_tpu.train import create_train_state, make_train_step

    mesh = make_mesh(MeshSpec(data=1))
    model, meta = zoo.create_model("resnet50")
    input_dtype = meta.input_dtype

    if uint8_input:

        class Uint8Normalize(nn.Module):
            """uint8 NHWC in; dequantize+normalize on device in bf16.
            Models the H2D-lean input path (the data loader ships raw
            bytes; normalization constants baked into the graph). The
            wrapped model is a FIELD so flax binds it as a submodule."""

            inner: nn.Module

            @nn.compact
            def __call__(self, x, train=True):
                x = x.astype(jnp.bfloat16) * jnp.bfloat16(1.0 / 255.0)
                x = (x - jnp.bfloat16(0.45)) * jnp.bfloat16(1.0 / 0.225)
                return self.inner(x, train=train)

        model = Uint8Normalize(inner=model)
        input_dtype = jnp.uint8

    tx, _ = make_optimizer(
        0.01, momentum=0.9, weight_decay=1e-4, lr_schedule="const",
        dataset="imagenet", num_batches_per_epoch=1,
    )
    init_x = (
        jnp.zeros((1, 224, 224, 3), input_dtype)
    )
    state = create_train_state(jax.random.PRNGKey(0), model, init_x, tx)
    step = make_train_step(
        model, meta, tx, mesh, None, compute_dtype=jnp.bfloat16,
        donate=True,
    )
    rs = np.random.RandomState(0)
    if uint8_input:
        x = jnp.asarray(
            rs.randint(0, 256, (1, batch, 224, 224, 3)), jnp.uint8
        )
    else:
        x = jnp.asarray(rs.randn(1, batch, 224, 224, 3), jnp.float32)
    bd = {
        "x": x,
        "y": jnp.asarray(rs.randint(0, 1000, (1, batch)), jnp.int32),
    }
    compiled = step.lower(state, bd).compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    flops = float(cost.get("flops", 0.0))
    nbytes = float(cost.get("bytes accessed", 0.0))
    return compiled, state, bd, flops, nbytes


def _time_row(compiled, state, bd, iters):
    for _ in range(5):
        state, metrics = compiled(state, bd)
    float(metrics["loss"])  # sync anchor
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = compiled(state, bd)
    loss = float(metrics["loss"])  # ONE end sync brackets the chain
    dt = (time.perf_counter() - t0) / iters
    import math

    assert math.isfinite(loss), f"non-finite loss {loss}"
    return dt


def run_rows(iters):
    import jax

    # device-kind-keyed peak (shared package table) instead of a
    # hardcoded v5e constant: mfu on any other device would be wrong
    from mgwfbp_tpu.utils.platform import peak_flops

    peak = peak_flops(jax.devices()[0].device_kind)
    rows = {}

    def measure(name, batch, uint8_input=False, bn_bf16=False):
        prior = os.environ.get("MGWFBP_BN_DTYPE")
        if bn_bf16:
            os.environ["MGWFBP_BN_DTYPE"] = "bfloat16"
        else:
            # rows labeled baseline must BE the baseline even if the
            # caller exported the knob globally
            os.environ.pop("MGWFBP_BN_DTYPE", None)
        try:
            compiled, state, bd, flops, nbytes = _build(
                batch, uint8_input=uint8_input
            )
            dt = _time_row(compiled, state, bd, iters)
        finally:
            if prior is None:
                os.environ.pop("MGWFBP_BN_DTYPE", None)
            else:
                os.environ["MGWFBP_BN_DTYPE"] = prior
        del compiled, state, bd
        rows[name] = {
            "batch": batch,
            "sec_per_iter": round(dt, 6),
            "images_per_sec": round(batch / dt, 1),
            "mfu": round(flops / dt / peak, 4) if peak else None,
            "flops_per_step": flops,
            "xla_bytes_accessed_GB": round(nbytes / 1e9, 3),
            "achieved_GBps_on_xla_bytes": round(nbytes / dt / 1e9, 1),
        }
        print(name, json.dumps(rows[name]), flush=True)

    measure("baseline_b128", 128)
    measure("batch_64", 64)
    measure("batch_256", 256)
    measure("uint8_input_b128", 128, uint8_input=True)
    measure("bf16_batchstats_b128", 128, bn_bf16=True)

    base = rows["baseline_b128"]
    for r in rows.values():
        r["throughput_vs_baseline"] = round(
            r["images_per_sec"] / base["images_per_sec"], 4
        )
    return {
        "protocol": (
            "AOT-compiled donated step, 5 warmup + "
            f"{iters} timed iters, ONE host sync after the last chained "
            "step; XLA cost analysis for flops/bytes"
        ),
        "device": jax.devices()[0].device_kind,
        "rows": rows,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--no-save", action="store_true")
    args = ap.parse_args(argv)
    from mgwfbp_tpu.utils.platform import apply_platform_overrides

    apply_platform_overrides()
    report = run_rows(args.iters)
    base = report["rows"]["baseline_b128"]
    verdicts = []
    for name in ("batch_64", "batch_256", "uint8_input_b128",
                 "bf16_batchstats_b128"):
        r = report["rows"][name]
        gain = r["images_per_sec"] / base["images_per_sec"] - 1.0
        verdicts.append(f"{name}: {gain:+.1%} img/s vs baseline")
    report["conclusion"] = verdicts
    print(json.dumps(report, indent=2))
    if not args.no_save and os.path.exists(ARTIFACT):
        art = json.load(open(ARTIFACT))
        art["ablations"] = report
        art["answer_v2"] = (
            "v2: the counterfactual rows are now MEASURED (see ablations) "
            "— the irreducibility claim rests on these, not on bandwidth "
            "accounting alone"
        )
        with open(ARTIFACT, "w") as f:
            json.dump(art, f, indent=1)
        print(f"updated {ARTIFACT}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
