"""Fold an lstman4 train.log into profiles/an4_real_audio.json (VERDICT
r4 #4: the real-audio WER trajectory must MOVE, not sit at 1.0).

Parses the trainer's per-epoch eval lines (loss + WER), summarizes the
trajectory, and rewrites the artifact's run section. The memorization run
evaluates the TRAIN split (data/an4_memcheck's val manifest lists the 45
real train utterances), so falling WER validates the full
spectrogram -> CTC -> greedy decode -> WER path end to end on real
speech; a separate held-out number on the 8-utterance real val split can
be appended with --val-wer once measured offline.

Usage:
  python tools/an4_report.py --log logs/.../train.log \
      --label "cpu memorization run" [--save]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

ARTIFACT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "profiles", "an4_real_audio.json",
)

# loss may be nan/inf on a diverged run, negative or scientific-notation on
# exotic configs — every such epoch must appear in the audit trajectory,
# not silently vanish because the number's spelling fell outside the
# pattern (ADVICE r5 #4)
_NUM = r"-?(?:[\d.]+(?:e-?\d+)?|nan|inf)"
_EVAL = re.compile(
    rf"epoch (\d+) eval: loss ({_NUM}), count {_NUM}, "
    rf"wer ({_NUM})"
)


def parse_log(path: str) -> list[dict]:
    rows = []
    with open(path) as f:
        for line in f:
            m = _EVAL.search(line)
            if m:
                rows.append(
                    {
                        "epoch": int(m.group(1)),
                        "eval_loss": float(m.group(2)),
                        "wer": float(m.group(3)),
                    }
                )
    return rows


def summarize(rows: list[dict], stride: int = 10) -> dict:
    import math

    if not rows:
        raise SystemExit("no eval lines found in log")
    finite = [r for r in rows if math.isfinite(r["wer"])]
    if not finite:
        raise SystemExit("every eval row is non-finite (diverged run)")
    best = min(finite, key=lambda r: r["wer"])
    # thin the trajectory for the artifact (every `stride` epochs + first,
    # best and last; stride <= 0 keeps all) so the JSON stays reviewable
    keep = {0, rows[-1]["epoch"], best["epoch"]}
    keep.update(
        r["epoch"] for r in rows if stride <= 0 or r["epoch"] % stride == 0
    )
    return {
        # named for what the log proves: epochs whose EVAL line appears
        # (with eval-every-N configs this is not the trained-epoch count)
        "last_eval_epoch": rows[-1]["epoch"],
        "evals": len(rows),
        "diverged_evals": len(rows) - len(finite),
        "best_wer": best["wer"],
        "best_wer_epoch": best["epoch"],
        "final_wer": rows[-1]["wer"],
        "wer_below_1.0": best["wer"] < 1.0,
        "trajectory": [r for r in rows if r["epoch"] in keep],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--log", required=True)
    ap.add_argument("--label", required=True,
                    help="run description recorded in the artifact, e.g. "
                         "'cpu memorization run, lr 1e-3'")
    ap.add_argument("--key", default="memorization_run",
                    help="artifact section to write")
    ap.add_argument("--val-wer", type=float, default=None,
                    help="held-out real-val WER measured offline")
    ap.add_argument("--stride", type=int, default=10)
    ap.add_argument("--save", action="store_true",
                    help="write into the artifact (default: print only)")
    args = ap.parse_args(argv)

    rows = parse_log(args.log)
    section = {
        "label": args.label,
        "log": os.path.relpath(args.log, os.path.dirname(ARTIFACT) + "/.."),
        **summarize(rows, stride=args.stride),
    }
    if args.val_wer is not None:
        section["held_out_val_wer"] = args.val_wer
        section["held_out_caveat"] = (
            "real val split is only 8 utterances (archive tail lost); "
            "the memorization number is the mechanism check, this one is "
            "directional"
        )
    print(json.dumps(section, indent=2))
    if args.save:
        art = json.load(open(ARTIFACT))
        art[args.key] = section
        with open(ARTIFACT, "w") as f:
            json.dump(art, f, indent=1)
        print(f"updated {ARTIFACT}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
