"""Measure comm/compute overlap of the production train step from a
jax.profiler trace (VERDICT r2 task #3: prove the overlap).

Runs the jitted MG-WFBP train step under `jax.profiler.trace`, then parses
the captured Chrome-trace JSON (plugins/profile/<run>/*.trace.json.gz) and
reports, per collective op, how much device compute executed concurrently
with it. This is the TPU analogue of the reference's per-merged-tensor
allreduce timers (reference distributed_optimizer.py:374-391,407-425), taken
from the device timeline instead of host timers.

Usage:
    python tools/overlap_report.py [--model resnet20] [--batch 16]
        [--policy mgwfbp] [--nsteps 1] [--out profiles/overlap.json]

Caveats: on a single real chip a cross-device all-reduce compiles away, so
collective rows only appear with >= 2 devices (e.g. the 8-device CPU mesh:
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8). On
CPU the collectives are synchronous thunks — the report then documents the
schedule, while TPU/GPU traces show true async concurrency.
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_COLLECTIVE_MARKERS = (
    "all-reduce", "all_reduce", "allreduce",
    "reduce-scatter", "all-gather", "collective-permute",
)
_NON_COMPUTE_MARKERS = _COLLECTIVE_MARKERS + (
    "copy", "infeed", "outfeed", "send", "recv", "tuple", "bitcast",
)


def _load_trace_events(logdir: str) -> list[dict]:
    paths = glob.glob(
        os.path.join(logdir, "plugins", "profile", "*", "*.trace.json.gz")
    )
    events: list[dict] = []
    for p in paths:
        with gzip.open(p, "rt") as f:
            data = json.load(f)
        events.extend(data.get("traceEvents", []))
    return events


def _device_lanes(events: list[dict]) -> set[tuple]:
    """(pid) ids of device (non-host) lanes, from process_name metadata."""
    lanes = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            name = e.get("args", {}).get("name", "").lower()
            if any(k in name for k in ("tpu", "device", "xla", "/stream", "gpu")):
                if "host" not in name and "python" not in name:
                    lanes.add(e.get("pid"))
    return lanes


def summarize_overlap(logdir: str) -> dict:
    """Parse a profiler trace dir -> overlap summary dict."""
    events = _load_trace_events(logdir)
    lanes = _device_lanes(events)
    complete = [
        e for e in events
        if e.get("ph") == "X" and (not lanes or e.get("pid") in lanes)
        and "dur" in e and "ts" in e
    ]
    colls = [
        e for e in complete
        if any(m in e.get("name", "").lower() for m in _COLLECTIVE_MARKERS)
    ]
    computes = [
        e for e in complete
        if not any(
            m in e.get("name", "").lower() for m in _NON_COMPUTE_MARKERS
        )
    ]
    rows = []
    for c in colls:
        c0, c1 = c["ts"], c["ts"] + c["dur"]
        concurrent = 0.0
        for k in computes:
            k0, k1 = k["ts"], k["ts"] + k["dur"]
            lo, hi = max(c0, k0), min(c1, k1)
            if hi > lo:
                concurrent += hi - lo
        rows.append(
            {
                "name": c["name"][:120],
                "dur_us": c["dur"],
                "concurrent_compute_us": round(concurrent, 3),
                "overlap_fraction": round(concurrent / max(c["dur"], 1e-9), 4),
            }
        )
    rows.sort(key=lambda r: -r["dur_us"])
    total = sum(r["dur_us"] for r in rows)
    overlapped = sum(r["concurrent_compute_us"] for r in rows)
    return {
        "n_collective_events": len(rows),
        "total_collective_us": round(total, 3),
        "overlapped_us": round(min(overlapped, total), 3),
        "overlap_fraction": round(overlapped / total, 4) if total else None,
        "collectives": rows[:40],
    }


def measure_tb(model, meta, params, batch_stats, batch):
    """One arrival-order backward profile for a model (shared by the
    _build_setup fallback and tools/policy_grid.py, which measures ONCE and
    feeds every policy's solve from the same numbers)."""
    import jax
    import jax.numpy as jnp

    from mgwfbp_tpu.parallel.allreduce import arrival_order
    from mgwfbp_tpu.profiling import benchmark_trainer_backward

    paths = jax.tree_util.tree_flatten_with_path(params)[0]
    names = [jax.tree_util.keystr(kp) for kp, _ in paths]
    perm = arrival_order(len(names), names=names)
    micro = {
        "x": jnp.zeros((batch,) + tuple(meta.input_shape), meta.input_dtype),
        "y": jnp.zeros((batch,), jnp.int32),
    }
    return benchmark_trainer_backward(
        model, meta, params, batch_stats, micro, perm,
        warmup=1, iters=3, names=names,
    )


def _build_setup(model_name, batch, policy, nsteps, comm_profile=None,
                 tb=None):
    """Shared setup: model/state/reducer (measured-tb schedule) + step fn.

    `tb`: pass a precomputed arrival-order backward profile so every policy
    of an A/B grid is solved AND simulated from the same measurement
    (tools/policy_grid.py measures once, reuses five times); by default tb
    is measured here for the policies that need it (mgwfbp/auto).
    """
    import jax
    import jax.numpy as jnp

    from mgwfbp_tpu import models as zoo
    from mgwfbp_tpu.optim import make_optimizer
    from mgwfbp_tpu.parallel.allreduce import arrival_order, make_merged_allreduce
    from mgwfbp_tpu.parallel.costmodel import load_profile, lookup_alpha_beta
    from mgwfbp_tpu.parallel.mesh import DATA_AXIS, MeshSpec, make_mesh
    from mgwfbp_tpu.profiling import benchmark_trainer_backward
    from mgwfbp_tpu.train import create_train_state, make_train_step

    n_dev = len(jax.devices())
    mesh = make_mesh(MeshSpec(data=n_dev))
    model, meta = zoo.create_model(model_name)
    tx, _ = make_optimizer(
        0.1, momentum=0.9, weight_decay=1e-4, lr_schedule="const",
        dataset=meta.dataset, num_batches_per_epoch=1,
    )
    state = create_train_state(
        jax.random.PRNGKey(0), model,
        jnp.zeros((1,) + tuple(meta.input_shape), meta.input_dtype), tx,
    )
    threshold = 0
    if policy.startswith("threshold:"):
        # "threshold:N" rows reproduce the reference's static-threshold
        # sweep (batch_dist_mpi.sh grid over element-count thresholds)
        policy, threshold = "threshold", int(policy.split(":", 1)[1])
    reducer = None
    if policy not in ("none", "xla"):
        from mgwfbp_tpu.parallel.costmodel import resolve_profile

        cost = (
            resolve_profile(load_profile(comm_profile), max(n_dev, 2))
            if comm_profile
            else lookup_alpha_beta("ici", max(n_dev, 2))
        )
        if tb is None and policy in ("mgwfbp", "auto"):
            tb = measure_tb(model, meta, state.params, state.batch_stats, batch)
        reducer = make_merged_allreduce(
            state.params, axis_name=DATA_AXIS, policy=policy,
            tb=tb, cost_model=cost, threshold=threshold,
        )
    step = make_train_step(
        model, meta, tx, mesh, reducer, nsteps_update=nsteps, donate=False
    )
    return mesh, model, meta, state, reducer, step, n_dev


def hlo_schedule_report(
    model_name: str, batch: int, policy: str, nsteps: int,
    comm_profile: str | None = None,
) -> dict:
    """Overlap evidence from the compiled module's instruction schedule:
    for each all-reduce in the ENTRY sequence, count the compute ops
    (fusions/convolutions/dots) scheduled BETWEEN it and the previous
    collective. Interleaved compute means each group's collective is issued
    as soon as its members' grads exist — the dataflow freedom the TPU
    latency-hiding scheduler turns into true async overlap — rather than
    all collectives piling up after the full backward (the lax.scan
    barrier failure mode, VERDICT r2 Weak #3)."""
    import re

    import jax
    import jax.numpy as jnp

    mesh, model, meta, state, reducer, step, n_dev = _build_setup(
        model_name, batch, policy, nsteps, comm_profile
    )
    gb = batch * n_dev
    bd = {
        "x": jnp.zeros((nsteps, gb) + tuple(meta.input_shape), meta.input_dtype),
        "y": jnp.zeros((nsteps, gb), jnp.int32),
    }
    text = step.lower(state, bd).compile().as_text()
    entry = text.split("ENTRY")[-1]
    lines = [l.strip() for l in entry.splitlines() if "=" in l]
    compute_pat = re.compile(r"fusion|convolution|dot\(|custom-call")
    rows = []
    since_prev = 0
    compute_after_first_ar = 0
    seen_ar = False
    for ln in lines:
        is_ar = "all-reduce(" in ln or "all-reduce-start(" in ln
        if is_ar:
            name = ln.split("=")[0].strip()[:60]
            rows.append({"collective": name, "compute_ops_since_prev": since_prev})
            since_prev = 0
            seen_ar = True
        elif compute_pat.search(ln):
            since_prev += 1
            if seen_ar:
                compute_after_first_ar += 1
    interleaved = sum(1 for r in rows[1:] if r["compute_ops_since_prev"] > 0)
    return {
        "mode": "hlo_schedule",
        "model": model_name,
        "policy": policy,
        "nsteps_update": nsteps,
        "n_devices": n_dev,
        "device_kind": jax.devices()[0].device_kind,
        "merge_groups": reducer.schedule.num_groups if reducer else 0,
        "n_collectives_in_schedule": len(rows),
        "collectives_with_compute_interleaved_before": interleaved,
        "compute_ops_scheduled_after_first_collective": compute_after_first_ar,
        "collectives": rows[:40],
    }


def capture_and_report(
    model_name: str, batch: int, policy: str, nsteps: int, steps: int = 5,
    comm_profile: str | None = None,
) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    mesh, model, meta, state, reducer, step, n_dev = _build_setup(
        model_name, batch, policy, nsteps, comm_profile
    )
    rs = np.random.RandomState(0)
    gb = batch * n_dev
    shape = (nsteps, gb) + tuple(meta.input_shape)
    bd = {
        "x": jnp.asarray(rs.randn(*shape)).astype(meta.input_dtype),
        "y": jnp.asarray(
            rs.randint(0, meta.num_classes, (nsteps, gb)), jnp.int32
        ),
    }
    state, m = step(state, bd)  # compile + warmup
    jax.block_until_ready(m)
    logdir = tempfile.mkdtemp(prefix="mgwfbp_trace_")
    with jax.profiler.trace(logdir):
        for _ in range(steps):
            state, m = step(state, bd)
        jax.block_until_ready(m)
    out = summarize_overlap(logdir)
    out.update(
        {
            "model": model_name,
            "policy": policy,
            "nsteps_update": nsteps,
            "n_devices": n_dev,
            "device_kind": jax.devices()[0].device_kind,
            "merge_groups": reducer.schedule.num_groups if reducer else 0,
            "trace_dir": logdir,
        }
    )
    if reducer is not None and reducer.schedule.predicted_group_times:
        # predicted-vs-actual per merged collective (reference logs the
        # prediction and times each merged tensor's allreduce in-loop,
        # distributed_optimizer.py:256-259, 374-391, 407-425). Alignment is
        # by rank order of duration/size: the trace does not carry group
        # identity, but the k-th largest collective should correspond to
        # the k-th largest bucket.
        pred = sorted(
            (
                {"bytes": b, "predicted_s": t}
                for b, t in reducer.schedule.predicted_group_times
            ),
            key=lambda r: -r["bytes"],
        )
        # aggregate the per-step events of each collective op (same HLO
        # instruction name recurs once per timed step) into a mean duration
        by_name: dict = {}
        for ev in out.get("collectives", []):
            agg = by_name.setdefault(ev["name"], {"total": 0.0, "n": 0})
            agg["total"] += ev["dur_us"]
            agg["n"] += 1
        actual = sorted(
            (
                {"name": k, "mean_us": v["total"] / v["n"]}
                for k, v in by_name.items()
            ),
            key=lambda r: -r["mean_us"],
        )[: len(pred)]
        rows = []
        for i, p in enumerate(pred):
            row = dict(p)
            if i < len(actual):
                meas = actual[i]["mean_us"] / 1e6
                row["measured_s"] = round(meas, 9)
                row["measured_over_predicted"] = (
                    round(meas / p["predicted_s"], 3)
                    if p["predicted_s"] > 0
                    else None
                )
            rows.append(row)
        out["predicted_vs_actual"] = rows
        out["alignment_caveat"] = (
            "rank-order alignment by duration: the trace's collective list "
            "also contains the metrics and batch_stats pmeans, so rows near "
            "the small-bucket tail may pair a bucket prediction with one of "
            "those; trust the large-bucket rows, and cross-check counts "
            "against merge_groups (+2 for metrics/bstats on BN models)"
        )
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="resnet20")
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--policy", default="mgwfbp")
    ap.add_argument("--nsteps", type=int, default=1)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--mode", choices=["trace", "hlo"], default="trace",
                    help="trace: profiler-timeline concurrency (needs "
                         "device lanes, i.e. TPU/GPU); hlo: compiled "
                         "schedule interleaving (any backend)")
    ap.add_argument("--comm-profile", dest="comm_profile", default=None,
                    help="calibrated alpha-beta json (profiles/*.json)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    from mgwfbp_tpu.utils.platform import apply_platform_overrides

    apply_platform_overrides()  # honor JAX_PLATFORMS despite sitecustomize
    if args.mode == "hlo":
        report = hlo_schedule_report(
            args.model, args.batch, args.policy, args.nsteps,
            comm_profile=args.comm_profile,
        )
    else:
        report = capture_and_report(
            args.model, args.batch, args.policy, args.nsteps, args.steps,
            comm_profile=args.comm_profile,
        )
    text = json.dumps(report, indent=2)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            f.write(text)
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
