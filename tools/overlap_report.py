"""Measure comm/compute overlap of the production train step from a
jax.profiler trace (VERDICT r2 task #3: prove the overlap).

Runs the jitted MG-WFBP train step under `jax.profiler.trace`, then parses
the captured Chrome-trace JSON (plugins/profile/<run>/*.trace.json.gz) and
reports, per collective op, how much device compute executed concurrently
with it. This is the TPU analogue of the reference's per-merged-tensor
allreduce timers (reference distributed_optimizer.py:374-391,407-425), taken
from the device timeline instead of host timers.

Usage:
    python tools/overlap_report.py [--model resnet20] [--batch 16]
        [--policy mgwfbp] [--nsteps 1] [--out profiles/overlap.json]

Caveats: on a single real chip a cross-device all-reduce compiles away, so
collective rows only appear with >= 2 devices (e.g. the 8-device CPU mesh:
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8). On
CPU the collectives are synchronous thunks — the report then documents the
schedule, while TPU/GPU traces show true async concurrency.
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
import tempfile

_COLLECTIVE_MARKERS = (
    "all-reduce", "all_reduce", "allreduce",
    "reduce-scatter", "all-gather", "collective-permute",
)
_NON_COMPUTE_MARKERS = _COLLECTIVE_MARKERS + (
    "copy", "infeed", "outfeed", "send", "recv", "tuple", "bitcast",
)


def _load_trace_events(logdir: str) -> list[dict]:
    paths = glob.glob(
        os.path.join(logdir, "plugins", "profile", "*", "*.trace.json.gz")
    )
    events: list[dict] = []
    for p in paths:
        with gzip.open(p, "rt") as f:
            data = json.load(f)
        events.extend(data.get("traceEvents", []))
    return events


def _device_lanes(events: list[dict]) -> set[tuple]:
    """(pid) ids of device (non-host) lanes, from process_name metadata."""
    lanes = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            name = e.get("args", {}).get("name", "").lower()
            if any(k in name for k in ("tpu", "device", "xla", "/stream", "gpu")):
                if "host" not in name and "python" not in name:
                    lanes.add(e.get("pid"))
    return lanes


def summarize_overlap(logdir: str) -> dict:
    """Parse a profiler trace dir -> overlap summary dict."""
    events = _load_trace_events(logdir)
    lanes = _device_lanes(events)
    complete = [
        e for e in events
        if e.get("ph") == "X" and (not lanes or e.get("pid") in lanes)
        and "dur" in e and "ts" in e
    ]
    colls = [
        e for e in complete
        if any(m in e.get("name", "").lower() for m in _COLLECTIVE_MARKERS)
    ]
    computes = [
        e for e in complete
        if not any(
            m in e.get("name", "").lower() for m in _NON_COMPUTE_MARKERS
        )
    ]
    rows = []
    for c in colls:
        c0, c1 = c["ts"], c["ts"] + c["dur"]
        concurrent = 0.0
        for k in computes:
            k0, k1 = k["ts"], k["ts"] + k["dur"]
            lo, hi = max(c0, k0), min(c1, k1)
            if hi > lo:
                concurrent += hi - lo
        rows.append(
            {
                "name": c["name"][:120],
                "dur_us": c["dur"],
                "concurrent_compute_us": round(concurrent, 3),
                "overlap_fraction": round(concurrent / max(c["dur"], 1e-9), 4),
            }
        )
    rows.sort(key=lambda r: -r["dur_us"])
    total = sum(r["dur_us"] for r in rows)
    overlapped = sum(r["concurrent_compute_us"] for r in rows)
    return {
        "n_collective_events": len(rows),
        "total_collective_us": round(total, 3),
        "overlapped_us": round(min(overlapped, total), 3),
        "overlap_fraction": round(overlapped / total, 4) if total else None,
        "collectives": rows[:40],
    }


def capture_and_report(
    model_name: str, batch: int, policy: str, nsteps: int, steps: int = 5
) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mgwfbp_tpu import models as zoo
    from mgwfbp_tpu.optim import make_optimizer
    from mgwfbp_tpu.parallel.allreduce import make_merged_allreduce
    from mgwfbp_tpu.parallel.costmodel import lookup_alpha_beta
    from mgwfbp_tpu.parallel.mesh import DATA_AXIS, MeshSpec, make_mesh
    from mgwfbp_tpu.train import create_train_state, make_train_step

    n_dev = len(jax.devices())
    mesh = make_mesh(MeshSpec(data=n_dev))
    model, meta = zoo.create_model(model_name)
    tx, _ = make_optimizer(
        0.1, momentum=0.9, weight_decay=1e-4, lr_schedule="const",
        dataset=meta.dataset, num_batches_per_epoch=1,
    )
    state = create_train_state(
        jax.random.PRNGKey(0), model,
        jnp.zeros((1,) + tuple(meta.input_shape), meta.input_dtype), tx,
    )
    reducer = None
    if policy not in ("none", "xla"):
        reducer = make_merged_allreduce(
            state.params, axis_name=DATA_AXIS, policy=policy,
            cost_model=lookup_alpha_beta("ici", max(n_dev, 2)),
        )
    step = make_train_step(
        model, meta, tx, mesh, reducer, nsteps_update=nsteps, donate=False
    )
    rs = np.random.RandomState(0)
    gb = batch * n_dev
    shape = (nsteps, gb) + tuple(meta.input_shape)
    bd = {
        "x": jnp.asarray(rs.randn(*shape), jnp.float32),
        "y": jnp.asarray(
            rs.randint(0, meta.num_classes, (nsteps, gb)), jnp.int32
        ),
    }
    state, m = step(state, bd)  # compile + warmup
    jax.block_until_ready(m)
    logdir = tempfile.mkdtemp(prefix="mgwfbp_trace_")
    with jax.profiler.trace(logdir):
        for _ in range(steps):
            state, m = step(state, bd)
        jax.block_until_ready(m)
    out = summarize_overlap(logdir)
    out.update(
        {
            "model": model_name,
            "policy": policy,
            "nsteps_update": nsteps,
            "n_devices": n_dev,
            "device_kind": jax.devices()[0].device_kind,
            "merge_groups": reducer.schedule.num_groups if reducer else 0,
            "trace_dir": logdir,
        }
    )
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="resnet20")
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--policy", default="mgwfbp")
    ap.add_argument("--nsteps", type=int, default=1)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    report = capture_and_report(
        args.model, args.batch, args.policy, args.nsteps, args.steps
    )
    text = json.dumps(report, indent=2)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            f.write(text)
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
