"""Real-data input-path benchmark (VERDICT r3 #4): is the host pipeline
fast enough to feed the chip?

The r3 bench drove synthetic in-memory arrays; nothing measured a real
epoch through file IO + decode + augmentation. This tool:

  1. (--make-data) materializes the REAL file formats under --data-dir:
     CIFAR-10 python-pickle batches (the torchvision on-disk layout
     `cifar-10-batches-py/data_batch_*`) and the reference's single-file
     HDF5 ImageNet (datasets.create_hdf5 — reference scripts/create_hdf5.py
     layout). Content is the synthetic twin (no egress in this container);
     the IO path — disk read, pickle/HDF5 decode, augmentation, batching —
     is exactly the real-data path.
  2. times Trainer-equivalent epochs over (a) in-memory synthetic and
     (b) the real files, with the production prefetch pipeline
     (PrefetchLoader) and with it disabled, reporting samples/s and the
     real/synthetic throughput ratio. On a TPU host the interesting number
     is the ratio at the bench batch: >= ~0.95 means the input path keeps
     up (reference feeds GPUs via DataLoader num_workers, dl_trainer.py:353).

Usage:
  python tools/input_bench.py --make-data --data-dir /tmp/mgwfbp_data
  python tools/input_bench.py --model resnet20 --data-dir /tmp/mgwfbp_data \
      --iters 200 --out profiles/input_pipeline_tpu.json
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_data(data_dir: str, imagenet_n: int = 4096) -> dict:
    import numpy as np

    from mgwfbp_tpu.data.datasets import create_hdf5, synthetic_images_hard

    os.makedirs(data_dir, exist_ok=True)
    report = {}
    # CIFAR-10 pickle batches (5 x 10k train + 10k test)
    root = os.path.join(data_dir, "cifar-10-batches-py")
    os.makedirs(root, exist_ok=True)
    train = synthetic_images_hard(50000, (32, 32, 3), 10, seed=0)
    test = synthetic_images_hard(10000, (32, 32, 3), 10, seed=1)
    for i in range(5):
        sel = slice(i * 10000, (i + 1) * 10000)
        with open(os.path.join(root, f"data_batch_{i+1}"), "wb") as f:
            pickle.dump(
                {
                    b"data": train.data[sel]
                    .transpose(0, 3, 1, 2)
                    .reshape(10000, -1),
                    b"labels": train.labels[sel].tolist(),
                },
                f,
            )
    with open(os.path.join(root, "test_batch"), "wb") as f:
        pickle.dump(
            {
                b"data": test.data.transpose(0, 3, 1, 2).reshape(10000, -1),
                b"labels": test.labels.tolist(),
            },
            f,
        )
    report["cifar10"] = root
    # single-file HDF5 ImageNet (reference key layout), synthetic content
    tr = synthetic_images_hard(imagenet_n, (224, 224, 3), 1000, seed=2)
    va = synthetic_images_hard(max(imagenet_n // 8, 128), (224, 224, 3),
                               1000, seed=3)
    path = os.path.join(data_dir, "imagenet.hdf5")
    create_hdf5(tr.data, tr.labels, va.data, va.labels, path)
    report["imagenet_hdf5"] = path
    report["imagenet_n"] = imagenet_n
    return report


def _time_loader(bundle, step_fn, state, iters, to_batch):
    """Drive the jitted step from the loader; end-sync via final loss pull."""
    import jax

    loader = bundle.train
    loader.set_epoch(0)
    n = 0
    t0 = time.perf_counter()
    m = None
    while n < iters:
        for raw in loader:
            state, m = step_fn(state, to_batch(raw))
            n += 1
            if n >= iters:
                break
        loader.set_epoch(n)  # new epoch if the set is small
    float(m["loss"])
    dt = (time.perf_counter() - t0) / n
    return dt, state


def run(model_name, data_dir, iters, warmup, out):
    from mgwfbp_tpu.utils.platform import apply_platform_overrides

    apply_platform_overrides()
    import jax
    import jax.numpy as jnp

    from mgwfbp_tpu import models as zoo
    from mgwfbp_tpu.config import PRESETS
    from mgwfbp_tpu.data import data_prepare
    from mgwfbp_tpu.optim import make_optimizer
    from mgwfbp_tpu.parallel.mesh import MeshSpec, make_mesh
    from mgwfbp_tpu.train import create_train_state, make_train_step

    preset = PRESETS.get(model_name, {})
    batch = preset.get("batch_size", 32)
    dataset = preset.get("dataset", "cifar10")
    model, meta = zoo.create_model(model_name)
    tx, _ = make_optimizer(
        0.1, lr_schedule="const", dataset=dataset, num_batches_per_epoch=1
    )
    mesh = make_mesh(MeshSpec(data=1))
    compute_dtype = jnp.bfloat16
    step = make_train_step(
        model, meta, tx, mesh, None, compute_dtype=compute_dtype,
        donate=False,
    )

    def to_batch(raw):
        if isinstance(raw, dict):
            return {k: jnp.asarray(v)[None] for k, v in raw.items()}
        x, y = raw
        return {"x": jnp.asarray(x)[None], "y": jnp.asarray(y)[None]}

    def fresh_state():
        return create_train_state(
            jax.random.PRNGKey(0), model,
            jnp.zeros((1,) + tuple(meta.input_shape), meta.input_dtype), tx,
        )

    results = {}
    configs = [
        ("synthetic_inmem", dict(synthetic=True), {}),
        ("real_files", dict(synthetic=None), {}),
        (
            "real_files_no_prefetch",
            dict(synthetic=None),
            {"MGWFBP_DATA_WORKERS": "0"},
        ),
    ]
    for name, kw, env in configs:
        old = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            bundle = data_prepare(
                dataset, data_dir=data_dir, batch_size=batch, **kw
            )
            if name != "synthetic_inmem" and bundle.synthetic:
                results[name] = {"error": f"no real {dataset} files under {data_dir}"}
                continue
            state = fresh_state()
            # warmup (compile + first batches)
            _, state = _time_loader(bundle, step, state, warmup, to_batch)
            dt, state = _time_loader(bundle, step, state, iters, to_batch)
            results[name] = {
                "sec_per_iter": round(dt, 6),
                "samples_per_sec": round(batch / dt, 2),
                "prefetch": type(bundle.train).__name__,
            }
        finally:
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
    if (
        "synthetic_inmem" in results
        and "real_files" in results
        and "sec_per_iter" in results.get("real_files", {})
    ):
        results["real_over_synthetic_throughput"] = round(
            results["synthetic_inmem"]["sec_per_iter"]
            / results["real_files"]["sec_per_iter"],
            4,
        )
    payload = {
        "model": model_name,
        "dataset": dataset,
        "batch": batch,
        "iters": iters,
        "device_kind": jax.devices()[0].device_kind,
        "data_dir": data_dir,
        "results": results,
    }
    text = json.dumps(payload, indent=1)
    if out:
        os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
        with open(out, "w") as f:
            f.write(text)
    print(text)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="resnet20")
    ap.add_argument("--data-dir", dest="data_dir", default="/tmp/mgwfbp_data")
    ap.add_argument("--make-data", action="store_true")
    ap.add_argument("--imagenet-n", type=int, default=4096)
    ap.add_argument("--iters", type=int, default=200)
    ap.add_argument("--warmup", type=int, default=20)
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    if args.make_data:
        print(json.dumps(make_data(args.data_dir, args.imagenet_n)))
        return 0
    return run(args.model, args.data_dir, args.iters, args.warmup, args.out)


if __name__ == "__main__":
    sys.exit(main())
