#!/usr/bin/env bash
# Chip-recovery work queue (r5): run the four chip legs in dependency
# order as soon as the tunnel serves compute again. Each leg logs under
# logs/chip_sequence/ and a failed leg does not block the later ones
# (they exercise independent paths). Calibration runs FIRST because it is
# the lightest leg (KB..MB payloads, minutes) and it produces
# profiles/tpu_v5e_family.json, which the bench leg — and the driver's
# end-of-round bench — load so their tails stop carrying the
# UNCALIBRATED-prior warning (VERDICT r4 #5).
#
# Usage: bash tools/chip_sequence.sh [logdir]
set -u
cd "$(dirname "$0")/.."
LOGDIR=${1:-logs/chip_sequence}
mkdir -p "$LOGDIR"

echo "[seq $(date -u +%H:%M:%S)] leg 1/4: calibrate --prior-extend ici"
timeout 2400 python -m mgwfbp_tpu.calibrate \
  --out profiles/tpu_v5e_family.json --prior-extend ici \
  >"$LOGDIR/calibrate.json" 2>"$LOGDIR/calibrate.err"
echo "[seq $(date -u +%H:%M:%S)] calibrate rc=$? $(cat "$LOGDIR/calibrate.json")"

echo "[seq $(date -u +%H:%M:%S)] leg 2/4: bench.py"
timeout 2400 python bench.py >"$LOGDIR/bench.json" 2>"$LOGDIR/bench.err"
echo "[seq $(date -u +%H:%M:%S)] bench rc=$? payload: $(cat "$LOGDIR/bench.json")"

echo "[seq $(date -u +%H:%M:%S)] leg 3/4: mfu_ablation"
timeout 3600 python tools/mfu_ablation.py \
  >"$LOGDIR/mfu_ablation.log" 2>&1
echo "[seq $(date -u +%H:%M:%S)] mfu rc=$?"

echo "[seq $(date -u +%H:%M:%S)] leg 4/4: AN4 memorization run (train-as-val)"
MGWFBP_WATCHDOG_S=900 timeout 7200 python -m mgwfbp_tpu.train_cli \
  --dnn lstman4 --data-dir data/an4_memcheck --max-epochs 300 \
  --logdir logs/an4_memcheck \
  >"$LOGDIR/an4_memcheck.log" 2>&1
echo "[seq $(date -u +%H:%M:%S)] an4 rc=$?"
echo "[seq $(date -u +%H:%M:%S)] done"
