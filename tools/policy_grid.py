"""Policy A/B grid on the available mesh: {mgwfbp, wfbp, single, none}
sec/iter for one model — the reference's core experimental method
(batch_dist_mpi.sh:1-17 thresholds x models; settings.py:34 oracle swap),
as one committed JSON artifact.

Usage:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python tools/policy_grid.py --model resnet20 --batch 8 \
    --comm-profile profiles/cpu8_mesh.json --out profiles/policy_grid_cpu8.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

POLICIES = ("mgwfbp", "auto", "wfbp", "single", "none")


def run_grid(model_name, batch, nsteps, comm_profile, iters, warmup):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from overlap_report import _build_setup  # shared measured-tb setup

    results = {}
    shared = None
    for policy in POLICIES:
        mesh, model, meta, state, reducer, step, n_dev = _build_setup(
            model_name, batch, policy, nsteps, comm_profile
        )
        gb = batch * n_dev
        rs = np.random.RandomState(0)
        bd = {
            "x": jnp.asarray(
                rs.randn(nsteps, gb, *meta.input_shape)
            ).astype(meta.input_dtype),
            "y": jnp.asarray(
                rs.randint(0, meta.num_classes, (nsteps, gb)), jnp.int32
            ),
        }
        s = state
        for _ in range(max(warmup, 1)):  # >=1: compile + sync anchor
            s, m = step(s, bd)
        float(m["loss"])
        # best-of-3 windows: host load noise on small shared boxes easily
        # exceeds the policy deltas; the minimum is the standard estimator
        # of the undisturbed time
        windows = []
        per_window = max(iters // 3, 1)
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(per_window):
                s, m = step(s, bd)
                loss = float(m["loss"])  # host sync each iter
            windows.append((time.perf_counter() - t0) / per_window)
        dt = min(windows)
        results[policy] = {
            "sec_per_iter": round(dt, 6),
            "window_secs": [round(w, 6) for w in windows],
            "samples_per_sec": round(gb / dt, 2),
            "merge_groups": (
                reducer.schedule.num_groups if reducer is not None else 0
            ),
            "predicted_nonoverlap_s": (
                reducer.schedule.predicted_nonoverlap_time
                if reducer is not None
                and reducer.schedule.predicted_nonoverlap_time
                == reducer.schedule.predicted_nonoverlap_time  # not NaN
                else None
            ),
            "predicted_total_s": (
                reducer.schedule.predicted_total_time
                if reducer is not None
                and reducer.schedule.predicted_total_time
                == reducer.schedule.predicted_total_time
                else None
            ),
            **(
                {"policy_detail": reducer.schedule.policy_detail}
                if reducer is not None and reducer.schedule.policy_detail
                else {}
            ),
        }
        shared = {
            "n_devices": n_dev,
            "device_kind": jax.devices()[0].device_kind,
            "global_batch": gb,
        }
        del s, step
    return {
        "model": model_name,
        "batch_per_device": batch,
        "nsteps_update": nsteps,
        "iters": iters,
        "comm_profile": comm_profile,
        **(shared or {}),
        "policies": results,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="resnet20")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--nsteps", type=int, default=1)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--comm-profile", dest="comm_profile", default=None)
    ap.add_argument("--note", default=None,
                    help="environment context recorded into the artifact")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    from mgwfbp_tpu.utils.platform import apply_platform_overrides

    apply_platform_overrides()
    report = run_grid(
        args.model, args.batch, args.nsteps, args.comm_profile,
        args.iters, args.warmup,
    )
    if args.note:
        report["environment_note"] = args.note
    text = json.dumps(report, indent=2)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            f.write(text)
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
