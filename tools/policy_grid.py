"""Policy A/B grid on the available mesh: {mgwfbp, wfbp, single, none}
sec/iter for one model — the reference's core experimental method
(batch_dist_mpi.sh:1-17 thresholds x models; settings.py:34 oracle swap),
as one committed JSON artifact.

Usage:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python tools/policy_grid.py --model resnet20 --batch 8 \
    --comm-profile profiles/cpu8_mesh.json --out profiles/policy_grid_cpu8.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

POLICIES = ("mgwfbp", "auto", "wfbp", "single", "none")


def _binom_tail_p(k: int, n: int) -> float:
    """One-sided sign-test p-value: P(X >= k) for X ~ Binomial(n, 0.5)."""
    from math import comb

    return sum(comb(n, i) for i in range(k, n + 1)) * 0.5 ** n


def run_grid(model_name, batch, nsteps, comm_profile, iters, warmup,
             rounds=5, policies=POLICIES, noise_control=True):
    """Interleaved A/B: build + warm every policy's step FIRST, then time
    them round-robin in `rounds` passes and keep each policy's best round.

    Sequential per-policy blocks (r3 protocol) let slow host-load drift
    masquerade as policy deltas — measured same-schedule pairs differed by
    up to 10% across blocks. Interleaving puts every policy under the same
    drift, and min-of-rounds drops transient stalls.

    noise_control adds a second, independently built+compiled instance of
    'single' under the name 'single#control'. The two rows run the
    IDENTICAL program, so their per-round paired deltas measure the pure
    measurement noise of this protocol on this host — the yardstick every
    policy-vs-policy delta must clear before it counts as a win
    (VERDICT r4 Weak #1: min-of-rounds alone understated a 6.6% floor).

    Memory note (ADVICE r4 #4): every policy's state + batch + compiled
    executable stays resident on device for the whole run, so peak device
    memory scales with len(policies). On the 8-virtual-CPU mesh this is
    host RAM and fine; on a real chip, large models (resnet50/vgg16 at
    preset batch) may OOM where a sequential protocol fit — shrink --batch
    or split --thresholds across invocations (each still carries the
    default policy set + noise pair, keeping in-run comparisons valid).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from overlap_report import _build_setup  # shared measured-tb setup

    # one backward profile feeds every policy's solve AND simulation — the
    # A/B must never compare schedules derived from different measurements
    from overlap_report import measure_tb

    from mgwfbp_tpu import models as zoo
    from mgwfbp_tpu.optim import make_optimizer
    from mgwfbp_tpu.train import create_train_state

    model0, meta0 = zoo.create_model(model_name)
    tx0, _ = make_optimizer(
        0.1, momentum=0.9, weight_decay=1e-4, lr_schedule="const",
        dataset=meta0.dataset, num_batches_per_epoch=1,
    )
    state0 = create_train_state(
        jax.random.PRNGKey(0), model0,
        jnp.zeros((1,) + tuple(meta0.input_shape), meta0.input_dtype), tx0,
    )
    tb = measure_tb(model0, meta0, state0.params, state0.batch_stats, batch)
    del state0

    if noise_control and "single" in policies:
        policies = tuple(policies) + ("single#control",)
    runs = {}
    shared = None
    for policy in policies:
        # "<policy>#<tag>" rows are independently built duplicates (the
        # identical-program noise pair); the tag is display-only
        mesh, model, meta, state, reducer, step, n_dev = _build_setup(
            model_name, batch, policy.split("#", 1)[0], nsteps,
            comm_profile, tb=tb
        )
        gb = batch * n_dev
        rs = np.random.RandomState(0)
        bd = {
            "x": jnp.asarray(
                rs.randn(nsteps, gb, *meta.input_shape)
            ).astype(meta.input_dtype),
            "y": jnp.asarray(
                rs.randint(0, meta.num_classes, (nsteps, gb)), jnp.int32
            ),
        }
        s = state
        for _ in range(max(warmup, 1)):  # >=1: compile + sync anchor
            s, m = step(s, bd)
        float(m["loss"])
        runs[policy] = {"step": step, "state": s, "batch": bd,
                        "reducer": reducer, "windows": []}
        shared = {
            "n_devices": n_dev,
            "device_kind": jax.devices()[0].device_kind,
            "global_batch": gb,
        }
    per_window = max(iters // rounds, 1)
    for _ in range(rounds):
        for policy in policies:
            r = runs[policy]
            s = r["state"]
            t0 = time.perf_counter()
            for _ in range(per_window):
                s, m = r["step"](s, r["batch"])
                loss = float(m["loss"])  # host sync each iter
            r["windows"].append((time.perf_counter() - t0) / per_window)
            r["state"] = s
    import statistics as _st

    results = {}
    for policy in policies:
        r = runs[policy]
        reducer = r["reducer"]
        dt = min(r["windows"])
        results[policy] = {
            "sec_per_iter": round(dt, 6),
            "median_sec_per_iter": round(_st.median(r["windows"]), 6),
            "window_secs": [round(w, 6) for w in r["windows"]],
            "samples_per_sec": round(shared["global_batch"] / dt, 2),
            "merge_groups": (
                reducer.schedule.num_groups if reducer is not None else 0
            ),
            "predicted_nonoverlap_s": (
                reducer.schedule.predicted_nonoverlap_time
                if reducer is not None
                and reducer.schedule.predicted_nonoverlap_time
                == reducer.schedule.predicted_nonoverlap_time  # not NaN
                else None
            ),
            "predicted_total_s": (
                reducer.schedule.predicted_total_time
                if reducer is not None
                and reducer.schedule.predicted_total_time
                == reducer.schedule.predicted_total_time
                else None
            ),
            **(
                {"policy_detail": reducer.schedule.policy_detail}
                if reducer is not None and reducer.schedule.policy_detail
                else {}
            ),
        }
    # prediction check (VERDICT r3 #1): the solver predicts bwd+comm, not
    # the full step (fwd/update and the virtual mesh's serialized per-device
    # compute are outside its model), so compare the INTER-POLICY deltas —
    # the quantity the schedule choice actually optimizes — predicted vs
    # measured, relative to the measured step.
    base = "wfbp"
    scheduled = [p for p in policies
                 if results[p].get("predicted_total_s") is not None]
    if base in scheduled:
        checks = {}
        for p in scheduled:
            if p == base:
                continue
            pred_d = (results[p]["predicted_total_s"]
                      - results[base]["predicted_total_s"])
            meas_d = (results[p]["sec_per_iter"]
                      - results[base]["sec_per_iter"])
            checks[f"{p}-vs-{base}"] = {
                "predicted_delta_s": round(pred_d, 6),
                "measured_delta_s": round(meas_d, 6),
                "gap_fraction_of_step": round(
                    abs(pred_d - meas_d)
                    / results[base]["sec_per_iter"], 4
                ),
            }
        prediction_check = checks
    else:
        prediction_check = None

    # ---- paired per-round statistics (VERDICT r4 #3) ----
    # Rounds are interleaved, so round i puts every policy under the same
    # host drift; the PAIRED per-round delta cancels that drift. The
    # identical-program pair (single vs single#control) bounds what pure
    # noise does to such a paired delta — a policy "wins" only when its
    # median paired delta clears that bound.
    med = {p: _st.median(runs[p]["windows"]) for p in policies}
    noise = None
    if "single#control" in runs and "single" in runs:
        nd = [
            runs["single"]["windows"][i] - runs["single#control"]["windows"][i]
            for i in range(len(runs["single"]["windows"]))
        ]
        med_abs = _st.median([abs(d) for d in nd])
        # robust bound: a single stalled round can blow the max |delta| to
        # >10x the typical round (observed 0.38 s vs 0.014 s median on an
        # idle host), which would mark EVERY comparison "inside noise".
        # The bound a policy's MEDIAN paired delta must clear is therefore
        # 3x the noise pair's median |delta| (max still reported).
        bound = 3.0 * med_abs
        noise = {
            "pair": ["single", "single#control"],
            "per_round_delta_s": [round(d, 6) for d in nd],
            "median_abs_delta_s": round(med_abs, 6),
            "max_abs_delta_s": round(max(abs(d) for d in nd), 6),
            "bound_s": round(bound, 6),
            "bound_rule": "3 * median |noise delta| (robust to stalled rounds)",
            "bound_frac_of_step": round(
                bound / min(med["single"], med["single#control"]), 4
            ),
        }
    # real policies only: the '#'-tagged control is a display duplicate and
    # must never be crowned the winner (its paired delta vs its twin is the
    # noise yardstick, not a competition)
    real = [p for p in policies if "#" not in p]
    best = min(real, key=lambda p: med[p])
    comparisons = {}
    beats, ties = [], []
    for p in policies:
        if p == best:
            continue
        # deltas are ROUNDED FIRST and every derived verdict field computed
        # from the rounded values: the artifact persists only 6-decimal
        # deltas, so a reader (tests/test_profiles.py pins this) must be
        # able to recompute slower_in_every_round / sign_test_p exactly —
        # a raw +3e-9 delta that rounds to 0.0 would otherwise publish a
        # "slower in every round" verdict its own artifact contradicts
        dl = [
            round(runs[p]["windows"][i] - runs[best]["windows"][i], 6)
            for i in range(len(runs[p]["windows"]))
        ]
        md = _st.median(dl)
        entry = {
            "per_round_delta_s": dl,
            "median_delta_s": round(md, 6),
            "median_delta_frac_of_step": round(md / med[best], 4),
            # magnitude-free evidence: a row slower than the winner in
            # EVERY interleaved round is a real loser even when the
            # magnitude bound is inflated (the noise pair duplicates
            # 'single', whose big pack buffers make it the most volatile
            # program in the grid — on vgg16 its deltas dwarf every other
            # row's, so the 3x-median bound alone calls everything a tie).
            # One-sided binomial tail for the OBSERVED positive count:
            # P(X >= k | n, 0.5) — 0.5**n only when slower in all rounds.
            # Both fields derive from the ROUNDED dl above (ADVICE r5 #2).
            "slower_in_every_round": all(d > 0 for d in dl),
            "sign_test_p": round(_binom_tail_p(
                sum(1 for d in dl if d > 0), len(dl)
            ), 4),
        }
        if noise is not None:
            outside = abs(md) > noise["bound_s"]
            entry["outside_noise"] = outside
            (beats if outside else ties).append(p)
        comparisons[f"{p}-vs-{best}"] = entry
    conclusion = {
        "fastest_by_median": best,
        "fastest_median_sec_per_iter": round(med[best], 6),
    }
    if noise is not None:
        conclusion["beats_outside_noise"] = beats
        conclusion["ties_within_noise"] = ties
        # real policies only: the '#'-tagged control is the noise
        # yardstick, not a competitor (same rule as the winner selection)
        conclusion["consistent_losers_sign_test"] = [
            p
            for p in real
            if p != best
            and comparisons[f"{p}-vs-{best}"]["slower_in_every_round"]
        ]
        conclusion["note"] = (
            f"'{best}' is fastest by median-of-rounds; rows in "
            "ties_within_noise are statistically indistinguishable from it "
            "(their median paired delta is inside 3x the identical-program "
            "noise pair's median |delta|). consistent_losers_sign_test "
            "lists rows slower than the winner in EVERY round — "
            "magnitude-free evidence (one-sided p = 0.5**rounds) that "
            "survives even when the volatile noise pair inflates the "
            "magnitude bound."
        )

    return {
        "model": model_name,
        "batch_per_device": batch,
        "nsteps_update": nsteps,
        "iters": iters,
        "rounds": rounds,
        "protocol": (
            "interleaved round-robin; per-policy min and median of rounds; "
            "paired per-round deltas vs identical-program noise pair"
        ),
        "comm_profile": comm_profile,
        **(shared or {}),
        "policies": results,
        **({"noise_pair": noise} if noise is not None else {}),
        "paired_deltas_vs_fastest": comparisons,
        "conclusion": conclusion,
        **(
            {"prediction_check_vs_wfbp": prediction_check}
            if prediction_check
            else {}
        ),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="resnet20")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--nsteps", type=int, default=1)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--comm-profile", dest="comm_profile", default=None)
    ap.add_argument("--thresholds", default=None,
                    help="comma list of element-count thresholds, each run "
                         "as an extra 'threshold:N' row ALONGSIDE the "
                         "default policy set (the reference's "
                         "batch_dist_mpi.sh static sweep)")
    ap.add_argument("--note", default=None,
                    help="environment context recorded into the artifact")
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--no-noise-control", dest="noise_control",
                    action="store_false",
                    help="skip the duplicate single#control row (saves one "
                         "resident executable on memory-tight devices; the "
                         "artifact then carries no outside/inside-noise "
                         "verdicts)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    from mgwfbp_tpu.utils.platform import apply_platform_overrides

    apply_platform_overrides()
    policies = POLICIES
    if args.thresholds:
        policies = tuple(
            f"threshold:{int(t)}" for t in args.thresholds.split(",")
        ) + POLICIES
    report = run_grid(
        args.model, args.batch, args.nsteps, args.comm_profile,
        args.iters, args.warmup, rounds=args.rounds, policies=policies,
        noise_control=args.noise_control,
    )
    if args.note:
        report["environment_note"] = args.note
    text = json.dumps(report, indent=2)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            f.write(text)
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
