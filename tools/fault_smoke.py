"""Fault-injection smoke: the resilience lifecycle, end to end, on the CPU
mesh (tools/check.sh stage).

Single-process (default) drives the REAL launcher twice through
subprocesses:

  1. a lenet run with ``MGWFBP_FAULT_PLAN=
     "nan@step=2;stall@secs=3,step=4;preempt@step=4"`` — must drop the
     NaN step (``bad_step`` event), write a flight-recorder postmortem
     bundle for it (ISSUE 12) that the live ``/postmortems`` endpoint
     serves MID-RUN (the stall before step 4 holds the run open long
     enough to probe), drain the injected SIGTERM gracefully
     (step-indexed checkpoint + ``preempt`` event) and exit rc 75
     (EX_TEMPFAIL, restart-friendly);
  2. the same command with no fault plan — must resume from the exact
     mid-epoch step (``resume`` event with mid_epoch) and finish rc 0.

``--processes 2`` runs the MULTI-HOST lifecycle instead (ISSUE 6): a
2-process CPU-mesh group under the auto-resubmit supervisor with
``preempt@step=4,proc=1`` signaling ONE process — the group must AGREE to
drain (the un-signaled process records signal ``PEER``), checkpoint once,
exit rc 75, get resubmitted, resume mid-epoch on both processes, and
finish; the per-process telemetry streams must merge into one monotonic
global timeline covering both incarnations (tools/telemetry_merge.py).
This stage is what keeps the multi-host path from rotting back into dead
code — the fate of the pre-ISSUE-6 multihost test, slow-marked and never
run while CPU collectives silently stayed unconfigured.

Both modes also smoke the LIVE observability plane (ISSUE 9): the
single-process faulted run is probed mid-run over HTTP (/metrics must
serve the live step counter, /healthz must answer 200), and the
2-process group must serve DISTINCT ports (base + process_index), each
reporting its own process_index in /status.

The 2-process mode additionally smokes the FLEET fan-in (ISSUE 10): the
supervisor's /fleet/status must answer MID-RUN with a live straggler
table naming BOTH processes (a fan-in hang fails check.sh's hard-timeout
stage, exactly like a coordination hang), /fleet/metrics must merge both
children under a `process` label, and the `fleet.json` http_sd sidecar
must persist both children's ACTUAL metrics endpoints.

``--chaos`` runs the SELF-HEALING lifecycle (ISSUE 20) against
drain-less faults the agreed-preempt machinery cannot survive on its
own: a mid-epoch SIGKILL (supervisor classifies the -9, shrinks to the
survivors, elastic-resumes off the last committed shard-native step)
and a 300 s wedge (alive, serving HTTP, not stepping — only the
liveness monitor's frozen-step verdict can see it; the heal must land
in bounded wall-clock time). Both scenarios pin failure/heal events in
the supervisor's own telemetry stream and a monotonic merged timeline
across the heal.

Asserts the telemetry lifecycle after each run. No accelerator, dataset,
or network needed.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

from mgwfbp_tpu.runtime.supervisor import free_port as _free_port  # noqa: E402

PREEMPT_RC = 75  # mirrors mgwfbp_tpu.utils.faults.PREEMPT_RC


def _probe(port: int, path: str, timeout_s: float = 1.0):
    """(http status, body) of one endpoint probe, or (None, reason)."""
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout_s
        ) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:  # 503 from /healthz is an answer
        return e.code, e.read().decode()
    except Exception as e:  # noqa: BLE001 — not up yet
        return None, str(e)


def _probe_post(port: int, path: str, doc: dict, timeout_s: float = 2.0):
    """(http status, body) of one POST probe, or (None, reason)."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:  # 4xx/5xx is an answer
        return e.code, e.read().decode()
    except Exception as e:  # noqa: BLE001 — not up yet
        return None, str(e)


def _cli(
    logdir: str, ckpt: bool = True, extra: tuple = (),
) -> list[str]:
    cmd = [
        sys.executable, "-m", "mgwfbp_tpu.train_cli",
        "--dnn", "lenet", "--synthetic", "--no-profile-backward",
        "--batch-size", "8", "--num-batches-per-epoch", "6",
        "--max-epochs", "2", "--epochs", "2", "--seed", "7",
        "--logdir", logdir,
    ]
    if ckpt:
        cmd += [
            "--checkpoint-dir", os.path.join(logdir, "ckpt"),
            "--ckpt-every-steps", "2",
        ]
    return cmd + ["--telemetry", *extra]


def _run(
    logdir: str, fault_plan: str, metrics_port: int = 0,
    ckpt: bool = True, extra: tuple = (),
) -> tuple[int, dict]:
    """One real-launcher run; with metrics_port > 0 the live plane is
    probed WHILE the run is up (mid-run, not post-hoc — that is the whole
    point of the plane). Returns (rc, probe results)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["MGWFBP_FAULT_PLAN"] = fault_plan
    env.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
    )
    if metrics_port:
        env["MGWFBP_METRICS_PORT"] = str(metrics_port)
    # child output goes to FILES, not pipes: this loop does not drain
    # while polling, and a chatty child filling a 64 KiB pipe buffer
    # would block forever (a structural hang the old capture_output
    # call never had)
    out_path = os.path.join(logdir, "fault_smoke_child.log")
    with open(out_path, "w") as sink:
        proc = subprocess.Popen(
            _cli(logdir, ckpt=ckpt, extra=extra), env=env, cwd=_ROOT,
            stdout=sink, stderr=subprocess.STDOUT,
        )
        probes: dict = {}
        deadline = time.monotonic() + 600
        while proc.poll() is None:
            if time.monotonic() > deadline:
                proc.kill()
                proc.wait()
                raise AssertionError("fault-smoke run timed out")
            if metrics_port and "metrics" not in probes:
                code, body = _probe(metrics_port, "/metrics")
                if code == 200 and "mgwfbp_steps_total" in body:
                    probes["metrics"] = body
                    code, body = _probe(metrics_port, "/healthz")
                    assert code == 200, f"/healthz mid-run: {code} {body}"
                    probes["healthz"] = body.strip()
            if metrics_port and "postmortems" not in probes:
                # the injected-NaN bad step must leave a flight-recorder
                # bundle that /postmortems lists WHILE the run is up
                # (the stall@step=4 in the plan holds the window open)
                code, body = _probe(metrics_port, "/postmortems")
                if code == 200:
                    doc = json.loads(body)
                    if doc.get("total", 0) >= 1 and doc.get("recent"):
                        probes["postmortems"] = doc
            time.sleep(0.1)
    with open(out_path) as f:
        tail = f.read()[-4000:]
    if proc.returncode not in (0, PREEMPT_RC):
        sys.stderr.write(tail)
    if metrics_port:
        assert "metrics" in probes, (
            "live /metrics endpoint never answered mid-run "
            f"(port {metrics_port}); child tail:\n" + tail
        )
    return proc.returncode, probes


def _events(logdir: str) -> list[dict]:
    from mgwfbp_tpu.telemetry import read_event_set

    paths = glob.glob(os.path.join(logdir, "*", "telemetry.jsonl"))
    assert len(paths) == 1, f"expected one telemetry stream, got {paths}"
    return read_event_set(paths[0])


def single_process() -> dict:
    from mgwfbp_tpu.telemetry import events_of

    with tempfile.TemporaryDirectory(prefix="mgwfbp_fault_smoke_") as d:
        port = _free_port()
        rc, probes = _run(
            d, "nan@step=2;stall@secs=3,step=4;preempt@step=4",
            metrics_port=port,
        )
        assert rc == PREEMPT_RC, (
            f"faulted run exited rc {rc}, want {PREEMPT_RC} (EX_TEMPFAIL)"
        )
        assert probes.get("healthz") == "ok", probes
        # the live /postmortems probe answered mid-run, naming the bundle
        pm_doc = probes.get("postmortems")
        assert pm_doc is not None, (
            "/postmortems never listed the injected-NaN bundle mid-run; "
            f"probes: {sorted(probes)}"
        )
        assert pm_doc["recent"][0]["trigger"] == "bad_step", pm_doc
        assert pm_doc["recent"][0]["step"] == 2, pm_doc
        recs = _events(d)
        bad = events_of(recs, "bad_step")
        assert bad and bad[0]["step"] == 2, f"bad_step missing/wrong: {bad}"
        assert bad[0]["nonfinite"] > 0
        (pre,) = events_of(recs, "preempt")
        assert pre["signal"] == "SIGTERM" and pre["iteration"] == 4, pre
        ckpts = events_of(recs, "checkpoint")
        assert any(c.get("mid_epoch") for c in ckpts), ckpts
        # ... and the bundle itself is on disk, atomic and complete,
        # naming the bad step (ISSUE 12 flight recorder)
        from mgwfbp_tpu.telemetry.recorder import list_bundles, read_bundle

        (tag_dir,) = [
            p for p in glob.glob(os.path.join(d, "*"))
            if os.path.isdir(os.path.join(p, "postmortems"))
        ]
        bundles = list_bundles(tag_dir)
        assert bundles, f"no postmortem bundle on disk under {d}"
        bundle = read_bundle(bundles[0])
        assert bundle["manifest"]["trigger"] == "bad_step", bundle
        assert bundle["manifest"]["step"] == 2, bundle["manifest"]
        assert any(
            r.get("event") == "bad_step" for r in bundle["events"]
        ), "ring dump lacks the triggering bad_step record"
        assert bundle.get("schedule"), "schedule state missing from bundle"

        rc, _ = _run(d, "")
        assert rc == 0, f"resume run exited rc {rc}"
        recs = _events(d)
        resumes = events_of(recs, "resume")
        assert resumes and resumes[-1]["mid_epoch"], resumes
        assert resumes[-1]["iteration"] == 4, resumes
        steps = events_of(recs, "step")
        assert max(s["step"] for s in steps) == 12, (
            "resumed run did not finish both epochs"
        )
        return {
            "fault_smoke": "ok",
            "bad_steps": len(bad),
            "preempt_iteration": pre["iteration"],
            "resume_iteration": resumes[-1]["iteration"],
            "final_step": max(s["step"] for s in steps),
            "live_metrics_probed": sorted(probes),
            "postmortem_bundle": bundle["manifest"]["path"],
        }


def multi_process(processes: int) -> dict:
    from mgwfbp_tpu.runtime.supervisor import Supervisor, default_train_cmd
    from mgwfbp_tpu.telemetry import events_of, find_stream_paths
    from telemetry_merge import check_monotonic, merge_streams

    with tempfile.TemporaryDirectory(prefix="mgwfbp_mh_smoke_") as d:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        # 4 virtual devices per process keeps the group's total world at
        # 8 — the same scale as tier-1 — and the incarnation under ~20 s
        env["MGWFBP_HOST_DEVICES"] = "4"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        # one plan for the whole group: NaN-poison a step on every
        # process, preempt ONLY process 1 — the drain must be agreed
        env["MGWFBP_FAULT_PLAN"] = "nan@step=2;preempt@step=4,proc=1"
        # live plane: one configured base port; child i must serve
        # base + i (telemetry/serve.resolve_metrics_port)
        base_port = _free_port()
        env["MGWFBP_METRICS_PORT"] = str(base_port)
        fleet_port = _free_port()
        sup = Supervisor(
            default_train_cmd(_cli(d)[3:]),  # strip interpreter/-m/module
            processes,
            backoff_base_s=0.2,
            log_dir=os.path.join(d, "supervisor"),
            env=env,
            fleet_port=fleet_port,
        )
        import threading

        rc_box: dict = {}
        runner = threading.Thread(
            target=lambda: rc_box.update(rc=sup.run()), daemon=True
        )
        runner.start()
        # mid-run: every process of the group serves a DISTINCT port,
        # each reporting its own process_index in /status; the
        # supervisor's FLEET fan-in must answer too, with a live
        # straggler table naming BOTH processes (hard-deadline bounded —
        # a fan-in hang must fail this stage, never wedge it)
        served: dict = {}
        fleet_doc = None
        fleet_metrics = None
        deadline = time.monotonic() + 590
        while runner.is_alive() and (
            len(served) < processes or fleet_doc is None
            or fleet_metrics is None
        ):
            if time.monotonic() > deadline:
                break
            for i in range(processes):
                if i in served:
                    continue
                code, body = _probe(base_port + i, "/status")
                if code == 200:
                    served[i] = json.loads(body)
            if fleet_doc is None:
                code, body = _probe(
                    fleet_port, "/fleet/status", timeout_s=10.0
                )
                if code == 200:
                    doc = json.loads(body)
                    named = {
                        r["process"]
                        for r in doc.get("straggler_table", [])
                    }
                    if named == set(range(processes)):
                        fleet_doc = doc
            if fleet_metrics is None:
                code, body = _probe(
                    fleet_port, "/fleet/metrics", timeout_s=10.0
                )
                if code == 200 and all(
                    f'mgwfbp_current_step{{process="{i}"}}' in body
                    for i in range(processes)
                ):
                    fleet_metrics = body
            time.sleep(0.1)
        runner.join(timeout=600)
        assert not runner.is_alive(), "supervised group wedged"
        rc = rc_box.get("rc")
        assert rc == 0, f"supervised group finished rc {rc}, want 0"
        assert set(served) == set(range(processes)), (
            f"live /status never answered on every per-process port "
            f"(base {base_port}): got {sorted(served)}"
        )
        for i, st in served.items():
            assert st["run"]["process_index"] == i, (i, st["run"])
        assert fleet_doc is not None, (
            "/fleet/status never served a live straggler table naming "
            f"every process (fleet port {fleet_port})"
        )
        assert fleet_doc["reachable"] == processes, fleet_doc
        assert fleet_metrics is not None, (
            "/fleet/metrics never merged every child under the process "
            "label"
        )
        # the http_sd sidecar persists the children's ACTUAL endpoints
        fleet_sd_path = os.path.join(d, "supervisor", "fleet.json")
        assert os.path.exists(fleet_sd_path), fleet_sd_path
        with open(fleet_sd_path) as f:
            sd = json.load(f)
        sd_procs = {g["labels"]["process"] for g in sd}
        assert sd_procs == {str(i) for i in range(processes)}, sd
        assert len(sup.results) == 2, (
            f"expected preempt + 1 resubmission, got "
            f"{[r.returncodes for r in sup.results]}"
        )
        assert sup.results[0].preempted, sup.results[0]
        assert sup.results[1].ok, sup.results[1]

        tag_dirs = [
            p for p in glob.glob(os.path.join(d, "*"))
            if os.path.isdir(p) and find_stream_paths(p)
        ]
        assert len(tag_dirs) == 1, f"expected one run dir, got {tag_dirs}"
        paths = find_stream_paths(tag_dirs[0])
        assert len(paths) == processes, (
            f"expected {processes} per-process streams, got {paths}"
        )
        merged = merge_streams(paths)
        check_monotonic(merged)
        pre = events_of(merged, "preempt")
        signals = {r["process"]: r["signal"] for r in pre}
        assert signals.get(1) == "SIGTERM", signals  # the signaled host
        assert signals.get(0) == "PEER", signals     # drained by agreement
        assert all(r["iteration"] == 4 for r in pre), pre
        resumes = events_of(merged, "resume")
        assert {r["process"] for r in resumes} == set(range(processes))
        assert all(
            r["mid_epoch"] and r["iteration"] == 4 for r in resumes
        ), resumes
        bad = events_of(merged, "bad_step")
        assert {r["process"] for r in bad} == set(range(processes))
        assert all(r["step"] == 2 for r in bad), bad
        for p in range(processes):
            last = max(
                r["step"] for r in events_of(merged, "step")
                if r["process"] == p
            )
            assert last == 12, f"process {p} stopped at step {last}"
        return {
            "fault_smoke": "ok",
            "processes": processes,
            "incarnations": [r.returncodes for r in sup.results],
            "merged_records": len(merged),
            "preempt_signals": signals,
            "metrics_ports": [base_port + i for i in range(processes)],
            "fleet_straggler_table": fleet_doc["straggler_table"],
            "fleet_sd_targets": sorted(
                t for g in sd for t in g["targets"]
            ),
        }


def resize_smoke(processes: int = 2, resize_to: int = 1) -> dict:
    """Elastic-resize lifecycle (ISSUE 13): a 2-process supervised group
    with ``--resize-to 1`` — the supervisor must TRIGGER the drain itself
    (SIGTERM once a child reports a completed step over /status), both
    processes must exit rc 75 with shard-native checkpoints committed
    exactly-once, the relaunched 1-process incarnation must find the
    2-process world's checkpoint under its sibling tag, re-shard it onto
    the new layout, emit the ``resize`` telemetry event, resume from the
    exact drained step, and finish — and the merged timeline across BOTH
    world sizes must stay monotonic. A hang anywhere fails check.sh's
    hard-timeout stage."""
    import threading

    from mgwfbp_tpu.runtime.supervisor import Supervisor, default_train_cmd
    from mgwfbp_tpu.telemetry import events_of, find_stream_paths
    from telemetry_merge import check_monotonic, merge_streams

    with tempfile.TemporaryDirectory(prefix="mgwfbp_resize_smoke_") as d:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["MGWFBP_HOST_DEVICES"] = "4"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        # the stall holds the run open so the supervisor's /status poll
        # reliably sees a completed step before the group finishes; the
        # drain itself comes from the supervisor, not the plan
        env["MGWFBP_FAULT_PLAN"] = "stall@secs=4,step=2"
        base_port = _free_port()
        env["MGWFBP_METRICS_PORT"] = str(base_port)
        fleet_port = _free_port()
        # rs_opt_ag: the opt state lives as 1/world shards — exactly the
        # state the shard-native format exists for; the 2-process save
        # must write per-process subtrees and the 1-process restore must
        # re-slice them, never a world-sized gather
        sup = Supervisor(
            default_train_cmd(_cli(d)[3:] + ["--comm-op", "rs_opt_ag"]),
            processes,
            backoff_base_s=0.2,
            log_dir=os.path.join(d, "supervisor"),
            env=env,
            fleet_port=fleet_port,
            resize_to=resize_to,
        )
        rc_box: dict = {}
        runner = threading.Thread(
            target=lambda: rc_box.update(rc=sup.run()), daemon=True
        )
        runner.start()
        # the transition is fleet-visible while it happens
        fleet_resize = None
        deadline = time.monotonic() + 560
        while runner.is_alive() and time.monotonic() < deadline:
            if fleet_resize is None:
                code, body = _probe(
                    fleet_port, "/fleet/status", timeout_s=10.0
                )
                if code == 200:
                    doc = json.loads(body)
                    if doc.get("resize"):
                        fleet_resize = doc["resize"]
            time.sleep(0.1)
        runner.join(timeout=600)
        assert not runner.is_alive(), "supervised resize group wedged"
        rc = rc_box.get("rc")
        assert rc == 0, f"supervised resize finished rc {rc}, want 0"
        assert len(sup.results) == 2, (
            f"expected drain + 1 resized incarnation, got "
            f"{[r.returncodes for r in sup.results]}"
        )
        assert sup.results[0].preempted, sup.results[0]
        assert len(sup.results[0].returncodes) == processes
        assert sup.results[1].ok, sup.results[1]
        assert len(sup.results[1].returncodes) == resize_to, (
            "resized incarnation launched at the wrong size:"
            f" {sup.results[1]}"
        )
        assert fleet_resize is not None, (
            "/fleet/status never surfaced the resize view"
        )
        assert fleet_resize["from"] == processes, fleet_resize
        assert fleet_resize["to"] == resize_to, fleet_resize

        # telemetry: streams from BOTH world sizes merge into one
        # monotonic timeline; the resized run records the transition
        tag_dirs = sorted(
            p for p in glob.glob(os.path.join(d, "*"))
            if os.path.isdir(p) and find_stream_paths(p)
        )
        assert len(tag_dirs) == 2, (
            f"expected one tag dir per world size, got {tag_dirs}"
        )
        paths = [p for t in tag_dirs for p in find_stream_paths(t)]
        assert len(paths) == processes + resize_to, paths
        merged = merge_streams(paths)
        check_monotonic(merged)
        resizes = events_of(merged, "resize")
        assert resizes, "no resize telemetry event recorded"
        rz = resizes[-1]
        assert rz["old_world"] == processes * 4, rz
        assert rz["new_world"] == resize_to * 4, rz
        assert rz["schedule_source"] == "relaunch-reshard", rz
        pre = events_of(merged, "preempt")
        assert len(pre) == processes, pre
        drained_iter = pre[0]["iteration"]
        assert all(r["iteration"] == drained_iter for r in pre), pre
        resumes = events_of(merged, "resume")
        assert resumes and resumes[-1]["iteration"] == drained_iter, (
            f"resumed at {resumes}, drained at {drained_iter}"
        )
        steps = [r["step"] for r in events_of(merged, "step")]
        assert max(steps) == 12, (
            f"resized run stopped at step {max(steps)}, want 12"
        )
        # shard-native payload really is per-process: the 2-process
        # world's committed step holds one subtree PER PROCESS whose
        # files carry exactly that process's shard rows — nothing
        # world-sized anywhere on disk
        n8_tag = [
            t for t in glob.glob(os.path.join(d, "ckpt", "*"))
            if "-n8-" in os.path.basename(t)
        ]
        assert n8_tag, os.listdir(os.path.join(d, "ckpt"))
        shard_steps = glob.glob(
            os.path.join(n8_tag[0], "sharded", "*", "manifest.json")
        )
        assert shard_steps, "2-process run committed no shard-native step"
        import numpy as _np

        with open(shard_steps[-1]) as f:
            manifest = json.load(f)
        rows = {
            p: doc["rows"] for p, doc in manifest["processes"].items()
        }
        assert sorted(r for v in rows.values() for r in v) == list(
            range(manifest["world"])
        ), rows
        step_dir = os.path.dirname(shard_steps[-1])
        for p, prows in rows.items():
            pdir = os.path.join(step_dir, f"p{int(p):05d}")
            for gi, shard in enumerate(manifest["layout"]["shard_sizes"]):
                arr = _np.load(
                    os.path.join(pdir, f"opt.s0.g{gi}.npy"), mmap_mode="r"
                )
                assert arr.shape == (len(prows), shard), (
                    p, gi, arr.shape, (len(prows), shard),
                )
        return {
            "fault_smoke": "ok",
            "mode": "resize",
            "incarnations": [r.returncodes for r in sup.results],
            "drained_iteration": drained_iter,
            "resize_event": {
                k: rz[k] for k in (
                    "old_world", "new_world", "schedule_source",
                )
            },
            "fleet_resize_view": fleet_resize,
            "merged_records": len(merged),
        }


def async_ckpt_smoke() -> dict:
    """ISSUE 16: the async shard writer's cost + event contract, on two
    clean (fault-free) runs. The async run must (a) write every
    mid-epoch --ckpt-every-steps checkpoint through the background
    writer (events carry async:true with the commit iteration), with at
    least one payload write demonstrably overlapping training (commit
    landing at a later iteration than the submit), and (b) keep
    post-warmup step time within noise of a checkpoints-OFF run — the
    step loop pays the shard-row snapshot and the group-agreed
    preamble, never the np.save."""
    from mgwfbp_tpu.telemetry import events_of

    def _post_warmup_median_step_s(d: str) -> float:
        steps = sorted(
            events_of(_events(d), "step"), key=lambda r: r["step"]
        )
        assert len(steps) >= 8, f"run too short: {len(steps)} steps"
        durs = sorted(float(r["dur_s"]) for r in steps[2:])
        return durs[len(durs) // 2]

    with tempfile.TemporaryDirectory(prefix="mgwfbp_async_off_") as d:
        rc, _ = _run(d, "", ckpt=False)
        assert rc == 0, f"ckpt-off run exited rc {rc}"
        off_median = _post_warmup_median_step_s(d)
    with tempfile.TemporaryDirectory(prefix="mgwfbp_async_on_") as d:
        rc, _ = _run(d, "")
        assert rc == 0, f"async-ckpt run exited rc {rc}"
        on_median = _post_warmup_median_step_s(d)
        recs = _events(d)
        mids = [
            c for c in events_of(recs, "checkpoint")
            if c.get("mid_epoch")
        ]
        assert mids, "no mid-epoch checkpoint events"
        assert all(c.get("async") for c in mids), (
            f"mid-epoch saves bypassed the async writer: {mids}"
        )
        assert all(
            int(c["commit_iteration"]) >= int(c["iteration"])
            for c in mids
        ), mids
        overlapped = [
            c for c in mids
            if int(c["commit_iteration"]) > int(c["iteration"])
        ]
        assert overlapped, (
            "every async save committed within its own submit step — "
            f"the payload write never overlapped training: {mids}"
        )
        # durations span submit -> commit, so each overlapping save's
        # duration covers at least the steps it rode over
        assert all(float(c["duration_s"]) > 0 for c in mids), mids
    # "within noise": a generous envelope (CPU CI boxes jitter), but one
    # a synchronous world-blocking save would still trip if the payload
    # write sat on the step path for a multi-ms np.save per 2 steps
    assert on_median <= off_median * 3.0 + 0.05, (
        f"async-ckpt median step {on_median * 1e3:.2f} ms vs ckpt-off "
        f"{off_median * 1e3:.2f} ms — the writer is back on the step "
        "path"
    )
    return {
        "async_ckpt_smoke": "ok",
        "ckpt_off_median_step_ms": round(off_median * 1e3, 3),
        "async_median_step_ms": round(on_median * 1e3, 3),
        "async_saves": len(mids),
        "overlapping_saves": len(overlapped),
        "max_overlap_steps": max(
            int(c["commit_iteration"]) - int(c["iteration"])
            for c in mids
        ),
    }


def serve_smoke() -> dict:
    """ISSUE 19: the in-process serving plane riding a real training run.
    A --serve-shadow run must answer POST /predict MID-RUN from the
    training process's own metrics port, the served step must ADVANCE as
    later mid-epoch commits hot-reload (reload + shadow_eval events in
    the stream, serving section in /status), and post-warmup median step
    time must stay within noise of an identical serve-off run — the
    serving plane lives entirely off the step path."""
    from mgwfbp_tpu.telemetry import events_of

    def _post_warmup_median_step_s(d: str) -> float:
        steps = sorted(
            events_of(_events(d), "step"), key=lambda r: r["step"]
        )
        assert len(steps) >= 8, f"run too short: {len(steps)} steps"
        durs = sorted(float(r["dur_s"]) for r in steps[2:])
        return durs[len(durs) // 2]

    with tempfile.TemporaryDirectory(prefix="mgwfbp_serve_off_") as d:
        rc, _ = _run(d, "")
        assert rc == 0, f"serve-off baseline exited rc {rc}"
        off_median = _post_warmup_median_step_s(d)

    from mgwfbp_tpu import models

    _, meta = models.create_model("lenet")
    inputs = [
        [[[0.5] * meta.input_shape[-1]] * meta.input_shape[1]]
        * meta.input_shape[0]
    ] * 2  # a batch of 2 constant images
    with tempfile.TemporaryDirectory(prefix="mgwfbp_serve_on_") as d:
        port = _free_port()
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
        )
        env["MGWFBP_METRICS_PORT"] = str(port)
        # two stalls hold the run open: the first while an EARLY commit
        # is being served, the second after later mid-epoch commits land
        # — the served step observed across them must advance
        env["MGWFBP_FAULT_PLAN"] = "stall@secs=4,step=3;stall@secs=4,step=9"
        out_path = os.path.join(d, "serve_smoke_child.log")
        first = advanced = serving_status = None
        with open(out_path, "w") as sink:
            proc = subprocess.Popen(
                _cli(d, extra=("--serve-shadow",)), env=env, cwd=_ROOT,
                stdout=sink, stderr=subprocess.STDOUT,
            )
            deadline = time.monotonic() + 600
            while proc.poll() is None:
                if time.monotonic() > deadline:
                    proc.kill()
                    proc.wait()
                    raise AssertionError("serve smoke run timed out")
                code, body = _probe_post(port, "/predict",
                                         {"inputs": inputs})
                if code == 200:
                    doc = json.loads(body)
                    if first is None:
                        first = doc
                    elif (advanced is None and int(doc["served_step"])
                          > int(first["served_step"])):
                        advanced = doc
                if advanced is not None and serving_status is None:
                    code, body = _probe(port, "/status")
                    if code == 200:
                        st = json.loads(body).get("serving")
                        if st and st.get("stats"):
                            serving_status = st
                time.sleep(0.1)
        with open(out_path) as f:
            tail = f.read()[-4000:]
        if proc.returncode != 0:
            sys.stderr.write(tail)
        assert proc.returncode == 0, f"serve-on run exited {proc.returncode}"
        assert first is not None, (
            "POST /predict never answered 200 mid-run; child tail:\n"
            + tail
        )
        assert advanced is not None, (
            "served step never advanced past the first served commit "
            f"(stuck at {first['served_step']})"
        )
        assert len(advanced["outputs"]) == 2, advanced
        assert len(advanced["outputs"][0]) == meta.num_classes, advanced
        assert serving_status is not None, (
            "/status never carried a serving section with request stats"
        )
        # the serve_stats emit is throttled (~1 s), so the snapshot may
        # trail the live request count — presence with >=1 is the pin
        assert serving_status["stats"]["requests"] >= 1, serving_status
        on_median = _post_warmup_median_step_s(d)
        recs = _events(d)
        reloads = events_of(recs, "reload")
        assert len(reloads) >= 2, f"fewer than 2 hot-reloads: {reloads}"
        rsteps = [int(r["step"]) for r in reloads]
        assert rsteps == sorted(rsteps), reloads
        # at least one reload served a MID-EPOCH commit (6 steps/epoch)
        assert any(s % 6 != 0 for s in rsteps), rsteps
        shadows = events_of(recs, "shadow_eval")
        assert shadows, "no shadow_eval events in the stream"
        assert all(
            float(s["loss"]) == float(s["loss"]) for s in shadows
        ), shadows  # NaN check
    # the plane is off the step path: a generous CPU-jitter envelope a
    # synchronous reload or an on-loop dispatcher would still trip
    assert on_median <= off_median * 3.0 + 0.05, (
        f"serve-on median step {on_median * 1e3:.2f} ms vs serve-off "
        f"{off_median * 1e3:.2f} ms — serving is back on the step path"
    )
    return {
        "serve_smoke": "ok",
        "first_served_step": int(first["served_step"]),
        "advanced_served_step": int(advanced["served_step"]),
        "reload_steps": rsteps,
        "shadow_evals": len(shadows),
        "requests_served": serving_status["stats"]["requests"],
        "serve_off_median_step_ms": round(off_median * 1e3, 3),
        "serve_on_median_step_ms": round(on_median * 1e3, 3),
    }


def chaos_smoke() -> dict:
    """ISSUE 20: the self-healing supervisor, end to end, against DRAIN-
    LESS faults — failures that never say goodbye, which the agreed-
    preempt machinery alone cannot survive.

    Scenario A (kill -> shrink): a 2-process group; ``kill@step=4,
    proc=1`` SIGKILLs process 1 mid-epoch (no drain, no checkpoint, no
    peer agreement). The survivor is left blocked in the merged
    collective; its ``MGWFBP_COORD_TIMEOUT_S`` deadline must convert
    the dead-peer hang into a clean rc-75 exit, the supervisor must
    classify the -9 as oom_kill and SHRINK to the 1 survivor (elastic
    resume off the last COMMITTED shard-native step — the manifest is
    the commit marker, so the resumed iteration is pinned against the
    manifests actually on disk), and the resumed world must finish all
    12 steps. failure/heal events land in the supervisor's own
    telemetry stream and the merged timeline across BOTH world sizes
    plus the supervisor stream stays monotonic.

    Scenario B (wedge -> bounded heal): ``wedge@step=3,secs=300,
    proc=1`` stops process 1 stepping for 300 s while it KEEPS serving
    HTTP — invisible to waitpid, invisible to /healthz. Only the
    liveness monitor (/status step frozen past MGWFBP_LIVENESS_GRACE_S)
    can see it; the group must be SIGTERMed, drain rc 75, relaunch at
    the same world, and finish — in wall-clock time far under both the
    300 s wedge and the 600 s barrier default. A slow detector or a
    barrier-length hang fails the elapsed-time pin (and check.sh's
    hard timeout)."""
    import threading

    from mgwfbp_tpu.runtime.supervisor import Supervisor, default_train_cmd
    from mgwfbp_tpu.telemetry import events_of, find_stream_paths
    from telemetry_merge import check_monotonic, merge_streams

    out: dict = {"fault_smoke": "ok", "mode": "chaos"}

    # ---- Scenario A: SIGKILL mid-epoch -> shrink to survivors --------
    with tempfile.TemporaryDirectory(prefix="mgwfbp_chaos_kill_") as d:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["MGWFBP_HOST_DEVICES"] = "4"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        # drain-less: process 1 dies with SIGKILL the moment it has
        # stepped past 4 — the inc=0 default keeps the fault out of the
        # healed incarnation (a drain-less fault resumes BELOW its own
        # step and would re-fire forever otherwise)
        env["MGWFBP_FAULT_PLAN"] = "kill@step=4,proc=1"
        # the survivor must give up on the dead peer's collective in
        # seconds, not DEFAULT_BARRIER_TIMEOUT_S — the bounded
        # coordination contract is half of what this scenario pins
        env["MGWFBP_COORD_TIMEOUT_S"] = "20"
        env["MGWFBP_METRICS_PORT"] = str(_free_port())
        # rs_opt_ag: sharded opt state, so the shrink really re-shards
        sup = Supervisor(
            default_train_cmd(_cli(d)[3:] + ["--comm-op", "rs_opt_ag"]),
            2,
            backoff_base_s=0.2,
            drain_grace_s=90.0,
            log_dir=os.path.join(d, "supervisor"),
            env=env,
        )
        rc_box: dict = {}
        runner = threading.Thread(
            target=lambda: rc_box.update(rc=sup.run()), daemon=True
        )
        runner.start()
        runner.join(timeout=600)
        assert not runner.is_alive(), "chaos kill group wedged"
        rc = rc_box.get("rc")
        assert rc == 0, f"chaos kill run finished rc {rc}, want 0"
        assert len(sup.results) == 2, (
            f"expected kill + 1 healed incarnation, got "
            f"{[r.returncodes for r in sup.results]}"
        )
        rcs0 = sup.results[0].returncodes
        assert rcs0[1] == -9, f"process 1 did not die by SIGKILL: {rcs0}"
        assert rcs0[0] == PREEMPT_RC, (
            f"survivor exited rc {rcs0[0]}, want {PREEMPT_RC} — the "
            "coordination deadline did not convert the dead-peer hang "
            "into a restart-friendly exit"
        )
        assert sup.processes == 1, (
            f"supervisor did not shrink to the survivor: {sup.processes}"
        )
        r1 = sup.results[1]
        assert r1.ok and len(r1.returncodes) == 1, r1

        # the commit marker is the manifest: resumed iteration must be
        # the LAST committed shard-native step of the 2-process world
        n8_tag = [
            t for t in glob.glob(os.path.join(d, "ckpt", "*"))
            if "-n8-" in os.path.basename(t)
        ]
        assert n8_tag, os.listdir(os.path.join(d, "ckpt"))
        committed = sorted(
            int(json.load(open(m))["step"]) for m in glob.glob(
                os.path.join(n8_tag[0], "sharded", "*", "manifest.json")
            )
        )
        assert committed, "no committed shard-native step survived"

        sup_stream = os.path.join(
            d, "supervisor", "telemetry.supervisor.jsonl"
        )
        assert os.path.exists(sup_stream), (
            "supervisor telemetry stream missing"
        )
        tag_dirs = sorted(
            p for p in glob.glob(os.path.join(d, "*"))
            if os.path.isdir(p) and find_stream_paths(p)
        )
        assert len(tag_dirs) == 2, (
            f"expected one tag dir per world size, got {tag_dirs}"
        )
        paths = [p for t in tag_dirs for p in find_stream_paths(t)]
        assert len(paths) == 3, paths  # 2 streams at n8, 1 at n4
        merged = merge_streams(paths + [sup_stream])
        check_monotonic(merged)
        fails = events_of(merged, "failure")
        oom = [r for r in fails if r["class"] == "oom_kill"]
        assert oom and oom[0]["target"] == "p1", fails
        assert oom[0]["process"] == -1, oom  # the supervisor's verdict
        heals = events_of(merged, "heal")
        shrinks = [r for r in heals if r["action"] == "shrink"]
        assert shrinks, heals
        assert shrinks[0]["old_world"] == 2, shrinks
        assert shrinks[0]["world"] == 1, shrinks
        resumes = events_of(merged, "resize")
        assert resumes and resumes[-1]["old_world"] == 8, resumes
        assert resumes[-1]["new_world"] == 4, resumes
        resumed = events_of(merged, "resume")
        assert resumed, "healed incarnation recorded no resume event"
        assert resumed[-1]["iteration"] == committed[-1], (
            f"resumed at iteration {resumed[-1]['iteration']}, but the "
            f"last committed shard-native step is {committed[-1]}"
        )
        last_step = max(r["step"] for r in events_of(merged, "step"))
        assert last_step == 12, (
            f"shrunk world stopped at step {last_step}, want 12"
        )
        out["kill"] = {
            "incarnations": [r.returncodes for r in sup.results],
            "shrunk_to": sup.processes,
            "committed_steps": committed,
            "resumed_iteration": resumed[-1]["iteration"],
            "merged_records": len(merged),
        }

    # ---- Scenario B: wedge -> liveness verdict -> bounded heal -------
    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix="mgwfbp_chaos_wedge_") as d:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["MGWFBP_HOST_DEVICES"] = "4"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        # process 1 stops stepping for 300 s at step 3 but keeps serving
        # HTTP — only the liveness monitor's frozen-step verdict can
        # see this failure class
        env["MGWFBP_FAULT_PLAN"] = "wedge@step=3,secs=300,proc=1"
        env["MGWFBP_LIVENESS_GRACE_S"] = "6"
        env["MGWFBP_COORD_TIMEOUT_S"] = "60"
        env["MGWFBP_METRICS_PORT"] = str(_free_port())
        sup = Supervisor(
            default_train_cmd(_cli(d)[3:]),
            2,
            backoff_base_s=0.2,
            drain_grace_s=90.0,
            log_dir=os.path.join(d, "supervisor"),
            env=env,
        )
        rc_box = {}
        runner = threading.Thread(
            target=lambda: rc_box.update(rc=sup.run()), daemon=True
        )
        runner.start()
        runner.join(timeout=600)
        assert not runner.is_alive(), "chaos wedge group wedged for real"
        healed_in = time.monotonic() - t0
        rc = rc_box.get("rc")
        assert rc == 0, f"chaos wedge run finished rc {rc}, want 0"
        # bounded: the heal must land in wall-clock time far under both
        # the 300 s wedge and the 600 s barrier default — this elapsed
        # pin is what makes "detected and healed in bounded time" a
        # checked property instead of a hope
        assert healed_in < 240, (
            f"wedge heal took {healed_in:.0f}s — the liveness monitor "
            "is not bounding detection"
        )
        assert len(sup.results) == 2, (
            f"expected wedge + 1 healed incarnation, got "
            f"{[r.returncodes for r in sup.results]}"
        )
        assert sup.results[0].returncodes == [PREEMPT_RC, PREEMPT_RC], (
            f"SIGTERMed group did not drain restart-friendly: "
            f"{sup.results[0].returncodes}"
        )
        assert sup.processes == 2, "wedge heal must NOT shrink the world"
        r1 = sup.results[1]
        assert r1.ok and len(r1.returncodes) == 2, r1

        sup_stream = os.path.join(
            d, "supervisor", "telemetry.supervisor.jsonl"
        )
        tag_dirs = sorted(
            p for p in glob.glob(os.path.join(d, "*"))
            if os.path.isdir(p) and find_stream_paths(p)
        )
        assert len(tag_dirs) == 1, tag_dirs  # same world both times
        paths = find_stream_paths(tag_dirs[0])
        assert len(paths) == 2, paths
        merged = merge_streams(paths + [sup_stream])
        check_monotonic(merged)
        fails = events_of(merged, "failure")
        wedged = [r for r in fails if r["class"] == "wedged"]
        # the wedged process freezes its peer at the next merged
        # collective inside the same grace window, so the verdict names
        # the frozen SET — the actually-wedged p1 must be in it
        assert wedged and "p1" in wedged[0]["target"].split(","), fails
        assert wedged[0]["process"] == -1, wedged  # the monitor's verdict
        heals = events_of(merged, "heal")
        rel = [r for r in heals if r["action"] == "relaunch"]
        assert rel and rel[0]["world"] == 2, heals
        resumed = events_of(merged, "resume")
        assert {r["process"] for r in resumed} == {0, 1}, resumed
        for p in range(2):
            last = max(
                r["step"] for r in events_of(merged, "step")
                if r["process"] == p
            )
            assert last == 12, f"process {p} stopped at step {last}"
        out["wedge"] = {
            "incarnations": [r.returncodes for r in sup.results],
            "healed_in_s": round(healed_in, 1),
            "wedged_failure_step": wedged[0].get("step"),
            "merged_records": len(merged),
        }
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--processes", type=int, default=1,
                    help="1 = single-process lifecycle (default); >1 = "
                         "supervised multi-host group with an agreed "
                         "drain + auto-resubmit")
    ap.add_argument("--resize", action="store_true",
                    help="elastic-resize lifecycle: 2-process supervised "
                         "group drained by the supervisor's --resize-to "
                         "policy, relaunched at 1 process from the "
                         "shard-native checkpoint, resumed to completion")
    ap.add_argument("--async-ckpt", action="store_true",
                    dest="async_ckpt",
                    help="async shard-writer lifecycle (ISSUE 16): "
                         "checkpoints-off vs async-ckpt step-time "
                         "envelope + async checkpoint event contract")
    ap.add_argument("--chaos", action="store_true",
                    help="self-healing lifecycle (ISSUE 20): SIGKILL a "
                         "process mid-epoch (supervisor shrinks to the "
                         "survivors off the last committed shard-native "
                         "step) and wedge one (liveness monitor heals "
                         "the group in bounded time)")
    ap.add_argument("--serve", action="store_true",
                    help="serving-plane lifecycle (ISSUE 19): "
                         "--serve-shadow run answering POST /predict "
                         "mid-run, served step advancing across "
                         "mid-epoch commits, step-time envelope vs a "
                         "serve-off run")
    args = ap.parse_args()
    if args.chaos:
        out = chaos_smoke()
    elif args.serve:
        out = serve_smoke()
    elif args.async_ckpt:
        out = async_ckpt_smoke()
    elif args.resize:
        out = resize_smoke(max(args.processes, 2), 1)
    elif args.processes > 1:
        out = multi_process(args.processes)
    else:
        out = single_process()
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
