"""Fault-injection smoke: the resilience lifecycle, end to end, on the CPU
mesh (tools/check.sh stage).

Drives the REAL launcher twice through subprocesses:

  1. a lenet run with ``MGWFBP_FAULT_PLAN="nan@step=2;preempt@step=4"`` —
     must drop the NaN step (``bad_step`` event), drain the injected
     SIGTERM gracefully (step-indexed checkpoint + ``preempt`` event) and
     exit rc 75 (EX_TEMPFAIL, restart-friendly);
  2. the same command with no fault plan — must resume from the exact
     mid-epoch step (``resume`` event with mid_epoch) and finish rc 0.

Asserts the telemetry lifecycle after each run. No accelerator, dataset,
or network needed.
"""

from __future__ import annotations

import glob
import json
import os
import subprocess
import sys
import tempfile

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

PREEMPT_RC = 75  # mirrors mgwfbp_tpu.utils.faults.PREEMPT_RC


def _cli(logdir: str) -> list[str]:
    return [
        sys.executable, "-m", "mgwfbp_tpu.train_cli",
        "--dnn", "lenet", "--synthetic", "--no-profile-backward",
        "--batch-size", "8", "--num-batches-per-epoch", "6",
        "--max-epochs", "2", "--epochs", "2", "--seed", "7",
        "--logdir", logdir,
        "--checkpoint-dir", os.path.join(logdir, "ckpt"),
        "--ckpt-every-steps", "2", "--telemetry",
    ]


def _run(logdir: str, fault_plan: str) -> int:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["MGWFBP_FAULT_PLAN"] = fault_plan
    env.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
    )
    proc = subprocess.run(
        _cli(logdir), env=env, cwd=_ROOT, capture_output=True, text=True,
        timeout=600,
    )
    if proc.returncode not in (0, PREEMPT_RC):
        sys.stderr.write(proc.stdout[-4000:] + proc.stderr[-4000:])
    return proc.returncode


def _events(logdir: str) -> list[dict]:
    from mgwfbp_tpu.telemetry import read_event_set

    paths = glob.glob(os.path.join(logdir, "*", "telemetry.jsonl"))
    assert len(paths) == 1, f"expected one telemetry stream, got {paths}"
    return read_event_set(paths[0])


def main() -> int:
    from mgwfbp_tpu.telemetry import events_of

    with tempfile.TemporaryDirectory(prefix="mgwfbp_fault_smoke_") as d:
        rc = _run(d, "nan@step=2;preempt@step=4")
        assert rc == PREEMPT_RC, (
            f"faulted run exited rc {rc}, want {PREEMPT_RC} (EX_TEMPFAIL)"
        )
        recs = _events(d)
        bad = events_of(recs, "bad_step")
        assert bad and bad[0]["step"] == 2, f"bad_step missing/wrong: {bad}"
        assert bad[0]["nonfinite"] > 0
        (pre,) = events_of(recs, "preempt")
        assert pre["signal"] == "SIGTERM" and pre["iteration"] == 4, pre
        ckpts = events_of(recs, "checkpoint")
        assert any(c.get("mid_epoch") for c in ckpts), ckpts

        rc = _run(d, "")
        assert rc == 0, f"resume run exited rc {rc}"
        recs = _events(d)
        resumes = events_of(recs, "resume")
        assert resumes and resumes[-1]["mid_epoch"], resumes
        assert resumes[-1]["iteration"] == 4, resumes
        steps = events_of(recs, "step")
        assert max(s["step"] for s in steps) == 12, (
            "resumed run did not finish both epochs"
        )
        print(json.dumps({
            "fault_smoke": "ok",
            "bad_steps": len(bad),
            "preempt_iteration": pre["iteration"],
            "resume_iteration": resumes[-1]["iteration"],
            "final_step": max(s["step"] for s in steps),
        }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
