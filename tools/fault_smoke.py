"""Fault-injection smoke: the resilience lifecycle, end to end, on the CPU
mesh (tools/check.sh stage).

Single-process (default) drives the REAL launcher twice through
subprocesses:

  1. a lenet run with ``MGWFBP_FAULT_PLAN="nan@step=2;preempt@step=4"`` —
     must drop the NaN step (``bad_step`` event), drain the injected
     SIGTERM gracefully (step-indexed checkpoint + ``preempt`` event) and
     exit rc 75 (EX_TEMPFAIL, restart-friendly);
  2. the same command with no fault plan — must resume from the exact
     mid-epoch step (``resume`` event with mid_epoch) and finish rc 0.

``--processes 2`` runs the MULTI-HOST lifecycle instead (ISSUE 6): a
2-process CPU-mesh group under the auto-resubmit supervisor with
``preempt@step=4,proc=1`` signaling ONE process — the group must AGREE to
drain (the un-signaled process records signal ``PEER``), checkpoint once,
exit rc 75, get resubmitted, resume mid-epoch on both processes, and
finish; the per-process telemetry streams must merge into one monotonic
global timeline covering both incarnations (tools/telemetry_merge.py).
This stage is what keeps the multi-host path from rotting back into dead
code — the fate of the pre-ISSUE-6 multihost test, slow-marked and never
run while CPU collectives silently stayed unconfigured.

Asserts the telemetry lifecycle after each run. No accelerator, dataset,
or network needed.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
import tempfile

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

PREEMPT_RC = 75  # mirrors mgwfbp_tpu.utils.faults.PREEMPT_RC


def _cli(logdir: str) -> list[str]:
    return [
        sys.executable, "-m", "mgwfbp_tpu.train_cli",
        "--dnn", "lenet", "--synthetic", "--no-profile-backward",
        "--batch-size", "8", "--num-batches-per-epoch", "6",
        "--max-epochs", "2", "--epochs", "2", "--seed", "7",
        "--logdir", logdir,
        "--checkpoint-dir", os.path.join(logdir, "ckpt"),
        "--ckpt-every-steps", "2", "--telemetry",
    ]


def _run(logdir: str, fault_plan: str) -> int:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["MGWFBP_FAULT_PLAN"] = fault_plan
    env.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
    )
    proc = subprocess.run(
        _cli(logdir), env=env, cwd=_ROOT, capture_output=True, text=True,
        timeout=600,
    )
    if proc.returncode not in (0, PREEMPT_RC):
        sys.stderr.write(proc.stdout[-4000:] + proc.stderr[-4000:])
    return proc.returncode


def _events(logdir: str) -> list[dict]:
    from mgwfbp_tpu.telemetry import read_event_set

    paths = glob.glob(os.path.join(logdir, "*", "telemetry.jsonl"))
    assert len(paths) == 1, f"expected one telemetry stream, got {paths}"
    return read_event_set(paths[0])


def single_process() -> dict:
    from mgwfbp_tpu.telemetry import events_of

    with tempfile.TemporaryDirectory(prefix="mgwfbp_fault_smoke_") as d:
        rc = _run(d, "nan@step=2;preempt@step=4")
        assert rc == PREEMPT_RC, (
            f"faulted run exited rc {rc}, want {PREEMPT_RC} (EX_TEMPFAIL)"
        )
        recs = _events(d)
        bad = events_of(recs, "bad_step")
        assert bad and bad[0]["step"] == 2, f"bad_step missing/wrong: {bad}"
        assert bad[0]["nonfinite"] > 0
        (pre,) = events_of(recs, "preempt")
        assert pre["signal"] == "SIGTERM" and pre["iteration"] == 4, pre
        ckpts = events_of(recs, "checkpoint")
        assert any(c.get("mid_epoch") for c in ckpts), ckpts

        rc = _run(d, "")
        assert rc == 0, f"resume run exited rc {rc}"
        recs = _events(d)
        resumes = events_of(recs, "resume")
        assert resumes and resumes[-1]["mid_epoch"], resumes
        assert resumes[-1]["iteration"] == 4, resumes
        steps = events_of(recs, "step")
        assert max(s["step"] for s in steps) == 12, (
            "resumed run did not finish both epochs"
        )
        return {
            "fault_smoke": "ok",
            "bad_steps": len(bad),
            "preempt_iteration": pre["iteration"],
            "resume_iteration": resumes[-1]["iteration"],
            "final_step": max(s["step"] for s in steps),
        }


def multi_process(processes: int) -> dict:
    from mgwfbp_tpu.runtime.supervisor import Supervisor, default_train_cmd
    from mgwfbp_tpu.telemetry import events_of, find_stream_paths
    from telemetry_merge import check_monotonic, merge_streams

    with tempfile.TemporaryDirectory(prefix="mgwfbp_mh_smoke_") as d:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        # 4 virtual devices per process keeps the group's total world at
        # 8 — the same scale as tier-1 — and the incarnation under ~20 s
        env["MGWFBP_HOST_DEVICES"] = "4"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        # one plan for the whole group: NaN-poison a step on every
        # process, preempt ONLY process 1 — the drain must be agreed
        env["MGWFBP_FAULT_PLAN"] = "nan@step=2;preempt@step=4,proc=1"
        sup = Supervisor(
            default_train_cmd(_cli(d)[3:]),  # strip interpreter/-m/module
            processes,
            backoff_base_s=0.2,
            log_dir=os.path.join(d, "supervisor"),
            env=env,
        )
        rc = sup.run()
        assert rc == 0, f"supervised group finished rc {rc}, want 0"
        assert len(sup.results) == 2, (
            f"expected preempt + 1 resubmission, got "
            f"{[r.returncodes for r in sup.results]}"
        )
        assert sup.results[0].preempted, sup.results[0]
        assert sup.results[1].ok, sup.results[1]

        tag_dirs = [
            p for p in glob.glob(os.path.join(d, "*"))
            if os.path.isdir(p) and find_stream_paths(p)
        ]
        assert len(tag_dirs) == 1, f"expected one run dir, got {tag_dirs}"
        paths = find_stream_paths(tag_dirs[0])
        assert len(paths) == processes, (
            f"expected {processes} per-process streams, got {paths}"
        )
        merged = merge_streams(paths)
        check_monotonic(merged)
        pre = events_of(merged, "preempt")
        signals = {r["process"]: r["signal"] for r in pre}
        assert signals.get(1) == "SIGTERM", signals  # the signaled host
        assert signals.get(0) == "PEER", signals     # drained by agreement
        assert all(r["iteration"] == 4 for r in pre), pre
        resumes = events_of(merged, "resume")
        assert {r["process"] for r in resumes} == set(range(processes))
        assert all(
            r["mid_epoch"] and r["iteration"] == 4 for r in resumes
        ), resumes
        bad = events_of(merged, "bad_step")
        assert {r["process"] for r in bad} == set(range(processes))
        assert all(r["step"] == 2 for r in bad), bad
        for p in range(processes):
            last = max(
                r["step"] for r in events_of(merged, "step")
                if r["process"] == p
            )
            assert last == 12, f"process {p} stopped at step {last}"
        return {
            "fault_smoke": "ok",
            "processes": processes,
            "incarnations": [r.returncodes for r in sup.results],
            "merged_records": len(merged),
            "preempt_signals": signals,
        }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--processes", type=int, default=1,
                    help="1 = single-process lifecycle (default); >1 = "
                         "supervised multi-host group with an agreed "
                         "drain + auto-resubmit")
    args = ap.parse_args()
    if args.processes > 1:
        out = multi_process(args.processes)
    else:
        out = single_process()
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
