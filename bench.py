"""Benchmark entry point — run by the driver on real TPU hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Measures the flagship workload (BASELINE.json headline config): ResNet-50 /
ImageNet-shaped synthetic data, full jitted train step (fwd+bwd+optimizer,
the same program `mgwfbp_tpu.train` runs in production) on the available
chip(s). vs_baseline is measured images/s divided by 250 img/s — a
P100-class single-GPU ResNet-50 fp32 throughput, i.e. one worker of the
paper's 4xP100 NCCL cluster (the reference repo publishes no numbers,
BASELINE.md; 250 img/s is the standard figure for that hardware class).
"""

from __future__ import annotations

import json
import os
import sys
import time

P100_RESNET50_IMG_S = 250.0


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mgwfbp_tpu import models as zoo
    from mgwfbp_tpu.optim import make_optimizer
    from mgwfbp_tpu.parallel.mesh import MeshSpec, make_mesh
    from mgwfbp_tpu.train import create_train_state, make_train_step

    batch = int(os.environ.get("MGWFBP_BENCH_BATCH", "32"))
    devices = jax.devices()
    mesh = make_mesh(MeshSpec(data=len(devices)))
    model, meta = zoo.create_model("resnet50")
    tx, _ = make_optimizer(
        0.01, momentum=0.9, weight_decay=1e-4, lr_schedule="const",
        dataset="imagenet", num_batches_per_epoch=1,
    )
    state = create_train_state(
        jax.random.PRNGKey(0), model, jnp.zeros((1, 224, 224, 3)), tx
    )
    step = make_train_step(model, meta, tx, mesh, None, donate=False)
    rs = np.random.RandomState(0)
    global_batch = batch * len(devices)
    x = jnp.asarray(rs.randn(1, global_batch, 224, 224, 3), jnp.float32)
    y = jnp.asarray(rs.randint(0, 1000, (1, global_batch)), jnp.int32)
    batch_dict = {"x": x, "y": y}

    # compile + warmup
    state, metrics = step(state, batch_dict)
    jax.block_until_ready(metrics)
    for _ in range(3):
        state, metrics = step(state, batch_dict)
    jax.block_until_ready(metrics)

    iters = int(os.environ.get("MGWFBP_BENCH_ITERS", "10"))
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, batch_dict)
    jax.block_until_ready(metrics)
    dt = (time.perf_counter() - t0) / iters
    img_s = global_batch / dt

    print(
        json.dumps(
            {
                "metric": "resnet50_synthetic_imagenet_train_throughput",
                "value": round(img_s, 2),
                "unit": "images/s",
                "vs_baseline": round(img_s / P100_RESNET50_IMG_S, 3),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
