"""Benchmark entry point — run by the driver on real TPU hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}. On an
unrecoverable failure it still prints one JSON line, with an "error" field
and value null, never a raw traceback (round-1 lesson: BENCH_r01.json was
rc=1 with nothing parseable, VERDICT.md Missing #1).

Measures the flagship workload (BASELINE.json headline config): ResNet-50 /
ImageNet-shaped synthetic data, full jitted train step (fwd+bwd+optimizer)
through the PRODUCTION MG-WFBP reducer path — bucketed pack/pmean/unpack per
merge group, the same program `mgwfbp_tpu.train` runs — on the available
chip(s). vs_baseline is measured images/s divided by 250 img/s: a P100-class
single-GPU ResNet-50 fp32 throughput, i.e. one worker of the reference
paper's 4xP100 NCCL cluster (the reference repo publishes no numbers,
BASELINE.md). Also reports an MFU estimate: XLA compiled-step FLOPs /
measured step time / chip peak.
"""

from __future__ import annotations

import json
import os
import sys
import time

P100_RESNET50_IMG_S = 250.0

# Peak dense-matmul FLOP/s per chip by device-kind substring (bf16 for TPU
# generations, fp32-ish for CPU fallback so MFU stays meaningful in smoke
# runs). Values are public datasheet numbers.
_PEAK_FLOPS = [
    ("v5 lite", 197e12),  # TPU v5e
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v4", 275e12),
    ("v6", 918e12),  # Trillium
    ("cpu", 1e11),
]


def _peak_flops(device_kind: str) -> float | None:
    kind = device_kind.lower()
    for sub, peak in _PEAK_FLOPS:
        if sub in kind:
            return peak
    return None


def _devices_with_retry(attempts: int = 4):
    """jax.devices() with backoff — backend init can transiently fail
    (UNAVAILABLE) if the chip/tunnel is briefly held. Clears cached backend
    state between attempts so the retry is real."""
    import jax

    delays = [5.0, 15.0, 30.0]
    last = None
    for i in range(attempts):
        try:
            return jax.devices()
        except RuntimeError as e:  # "Unable to initialize backend ..."
            last = e
            try:
                jax.extend.backend.clear_backends()
            except Exception:
                pass
            if i < attempts - 1:
                time.sleep(delays[min(i, len(delays) - 1)])
    raise RuntimeError(f"backend init failed after {attempts} attempts: {last}")


def _emit(payload: dict) -> None:
    print(json.dumps(payload), flush=True)


def run_bench() -> dict:
    from mgwfbp_tpu.utils.platform import apply_platform_overrides

    apply_platform_overrides()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mgwfbp_tpu import models as zoo
    from mgwfbp_tpu.optim import make_optimizer
    from mgwfbp_tpu.parallel.allreduce import make_merged_allreduce
    from mgwfbp_tpu.parallel.costmodel import lookup_alpha_beta
    from mgwfbp_tpu.parallel.mesh import DATA_AXIS, MeshSpec, make_mesh
    from mgwfbp_tpu.train import create_train_state, make_train_step

    batch = int(os.environ.get("MGWFBP_BENCH_BATCH", "32"))
    model_name = os.environ.get("MGWFBP_BENCH_MODEL", "resnet50")
    policy = os.environ.get("MGWFBP_BENCH_POLICY", "mgwfbp")

    devices = _devices_with_retry()
    n_dev = len(devices)
    mesh = make_mesh(MeshSpec(data=n_dev))
    model, meta = zoo.create_model(model_name)
    tx, _ = make_optimizer(
        0.01, momentum=0.9, weight_decay=1e-4, lr_schedule="const",
        dataset="imagenet", num_batches_per_epoch=1,
    )
    state = create_train_state(
        jax.random.PRNGKey(0), model, jnp.zeros((1, 224, 224, 3)), tx
    )
    if policy == "none":
        reducer = None  # XLA-fused oracle, for A/B via env only
    else:
        reducer = make_merged_allreduce(
            state.params,
            axis_name=DATA_AXIS,
            policy=policy,
            cost_model=lookup_alpha_beta("ici", max(n_dev, 2)),
        )
    step = make_train_step(model, meta, tx, mesh, reducer, donate=False)
    rs = np.random.RandomState(0)
    global_batch = batch * n_dev
    batch_dict = {
        "x": jnp.asarray(rs.randn(1, global_batch, 224, 224, 3), jnp.float32),
        "y": jnp.asarray(rs.randint(0, 1000, (1, global_batch)), jnp.int32),
    }

    # compile + warmup
    state, metrics = step(state, batch_dict)
    jax.block_until_ready(metrics)
    for _ in range(3):
        state, metrics = step(state, batch_dict)
    jax.block_until_ready(metrics)

    iters = int(os.environ.get("MGWFBP_BENCH_ITERS", "10"))
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, batch_dict)
    jax.block_until_ready(metrics)
    dt = (time.perf_counter() - t0) / iters
    img_s = global_batch / dt

    # MFU estimate: per-step FLOPs from the compiled program's cost analysis
    # over measured step time, against chip peak.
    mfu = None
    flops = None
    try:
        cost = step.lower(state, batch_dict).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0)) or None
    except Exception:
        flops = None
    peak = _peak_flops(devices[0].device_kind)
    if flops and peak:
        mfu = flops / dt / (peak * n_dev)

    payload = {
        "metric": f"{model_name}_synthetic_imagenet_train_throughput",
        "value": round(img_s, 2),
        "unit": "images/s",
        "vs_baseline": round(img_s / P100_RESNET50_IMG_S, 3),
        "policy": policy,
        "n_devices": n_dev,
        "device_kind": devices[0].device_kind,
        "sec_per_iter": round(dt, 5),
        "merge_groups": (
            reducer.schedule.num_groups if reducer is not None else 0
        ),
    }
    if mfu is not None:
        payload["mfu"] = round(mfu, 4)
    if flops is not None:
        payload["flops_per_step"] = flops
    return payload


def main() -> int:
    try:
        _emit(run_bench())
        return 0
    except Exception as e:  # noqa: BLE001 — one JSON line, never a traceback
        _emit(
            {
                "metric": "resnet50_synthetic_imagenet_train_throughput",
                "value": None,
                "unit": "images/s",
                "vs_baseline": None,
                "error": f"{type(e).__name__}: {e}",
            }
        )
        return 1


if __name__ == "__main__":
    sys.exit(main())
