"""Benchmark entry point — run by the driver on real TPU hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}. On an
unrecoverable failure it still prints one JSON line, with an "error" field
and value null, never a raw traceback.

Protocol (VERDICT r2 task #2 — a number that survives scrutiny):
  * the full policy grid {mgwfbp, wfbp, single, none} is timed in ONE run —
    the reference's whole experimental method is this A/B grid
    (reference batch_dist_mpi.sh:1-17, settings.py:34 ORIGINAL_HOROVOD);
  * the timed loop is host-synchronized by pulling a scalar computed by
    the LAST chained step: steps chain through donated state, so the device
    runs them strictly in order and the final pull brackets the whole
    region — real device execution even if block_until_ready were a no-op
    through an experimental backend. Intermediate pulls are avoided because
    one tunnel round trip costs ~50 ms here (MGWFBP_BENCH_SYNC=iter|window
    restores per-step/per-10-step pulls for harness A/B);
  * >= 50 timed iterations at the model's PRESET per-worker batch
    (resnet50: 128, reference exp_configs/resnet50.conf), falling back to
    batch 64 only on OOM (reported in the payload);
  * MFU is computed from XLA's compiled cost analysis; a physically
    impossible MFU (> 1.0) turns the result into an "error" payload rather
    than reporting garbage (BENCH_r02 reported MFU 1.89).

The mgwfbp policy uses a MEASURED total-backward time to scale its tb
profile (no invented 1e-3 constants).
"""

from __future__ import annotations

import json
import os
import sys
import time

P100_RESNET50_IMG_S = 250.0

_POLICIES = ("mgwfbp", "auto", "wfbp", "single", "none")


def _peak_flops(device_kind: str):
    """Device-kind-keyed peak FLOP/s (shared table in utils.platform)."""
    from mgwfbp_tpu.utils.platform import peak_flops

    return peak_flops(device_kind)


class ChipUnavailable(RuntimeError):
    """Backend init timed out on every attempt: there is no chip to
    measure. Distinct from a real failure so the bench can emit a
    structured "skipped" record (exit 0) — the perf trajectory must be
    able to tell "no chip this round" from "regression" (BENCH_r01..r05
    all carried this outage as rc=1 null metrics)."""


def _devices_with_retry(
    attempts: int = 4,
    init_timeout_s: float = 240.0,
    timeout_attempts: int = 3,
):
    """jax.devices() with backoff — backend init can transiently fail
    (UNAVAILABLE) if the chip/tunnel is briefly held.

    Init also runs under a watchdog: a wedged remote chip makes the PJRT
    client BLOCK INDEFINITELY inside make_c_api_client waiting for the
    pool grant (observed: a killed client's server-side grant pinned the
    chip for hours and every new client hung). A bench that hangs can
    never print its one JSON line. A timed-out init is retried up to
    `timeout_attempts` times with exponential backoff (the pool sometimes
    releases a stale grant minutes later); when every attempt times out
    the outage is raised as ChipUnavailable so main() can emit the
    structured "skipped" record instead of an error.
    """
    import jax

    from mgwfbp_tpu.utils.faults import FaultPlan
    from mgwfbp_tpu.utils.platform import DeadlineExceeded, run_with_deadline

    # deterministic fault injection (MGWFBP_FAULT_PLAN=chip_unavailable):
    # exercise the structured-skip path — every retry "times out" without
    # the real multi-minute waits, then the outage surfaces exactly like a
    # genuinely wedged grant (bench_skip record, rc 0)
    if FaultPlan.from_env().chip_unavailable():
        raise ChipUnavailable(
            f"backend init timed out after {init_timeout_s:.0f}s in each "
            f"of {timeout_attempts} attempts — chip/tunnel unavailable "
            "(injected by MGWFBP_FAULT_PLAN=chip_unavailable)"
        )

    delays = [5.0, 15.0, 30.0]
    last = None
    errors = 0
    timeouts = 0
    while True:
        try:
            return run_with_deadline(
                jax.devices, init_timeout_s, what="backend init"
            )
        except DeadlineExceeded:
            timeouts += 1
            _progress(
                f"backend init timed out after {init_timeout_s:.0f}s "
                f"(attempt {timeouts}/{timeout_attempts})"
            )
            if timeouts >= timeout_attempts:
                raise ChipUnavailable(
                    f"backend init timed out after {init_timeout_s:.0f}s in "
                    f"each of {timeouts} attempts — chip/tunnel unavailable "
                    "(client blocked waiting for the device grant)"
                ) from None
            delay = 30.0 * (2 ** (timeouts - 1))  # 30s, 60s, ...
            # do NOT clear_backends here: the abandoned init thread is
            # still blocked INSIDE xla_bridge holding the backend lock,
            # and _clear_backends takes that same lock with no deadline —
            # it would hang the main thread forever, un-printing the one
            # JSON line this whole retry dance exists to guarantee
            clear = False
        except Exception as e:  # noqa: BLE001 — filtered below
            last = e
            if not isinstance(last, RuntimeError):
                # only RuntimeError ("Unable to initialize backend",
                # transient UNAVAILABLE) is worth retrying; config/import
                # errors are deterministic — surface them immediately
                raise last
            errors += 1
            if errors >= attempts:
                raise RuntimeError(
                    f"backend init failed after {attempts} attempts: {last}"
                )
            delay = delays[min(errors - 1, len(delays) - 1)]
            clear = True  # init FAILED (thread exited, lock released):
            # clearing the half-initialized backend is safe and needed
        if clear:
            try:
                jax.extend.backend.clear_backends()
            except Exception:
                pass
        time.sleep(delay)


def _emit(payload: dict) -> None:
    print(json.dumps(payload), flush=True)


def _progress(msg: str) -> None:
    """Phase marker on stderr (stdout carries exactly one JSON line).

    The r5 chip outage wedged mid-run with nothing between the init
    warning and the driver's timeout — phase markers make the next wedge
    diagnosable from the stderr tail alone."""
    print(f"[bench {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


def _compute_preflight(
    attempts: int = 2, deadline_s: float = 180.0
) -> None:
    """Fail fast when the device accepts a session but executes nothing.

    Observed r5 outage mode (distinct from the r4 init wedge): jax.devices()
    returns instantly, then the FIRST real computation — even a 128x128
    matmul — blocks forever server-side. A bench that only guards init
    (_devices_with_retry) then hangs until the driver's timeout with no
    JSON line. This runs one trivial jitted program under a deadline with
    backoff retries, so a wedged-compute outage becomes an "error" payload
    in minutes. MGWFBP_BENCH_PREFLIGHT_S overrides the deadline; 0 skips.
    """
    import jax
    import jax.numpy as jnp

    from mgwfbp_tpu.utils.platform import DeadlineExceeded, run_with_deadline

    deadline_s = float(
        os.environ.get("MGWFBP_BENCH_PREFLIGHT_S", str(deadline_s))
    )
    if deadline_s <= 0:
        return

    def probe():
        x = jnp.ones((128, 128), jnp.float32)
        return float(jax.jit(lambda a: (a @ a).sum())(x))

    # ONE retry only: PJRT is thread-safe, so a fresh probe thread can
    # succeed after a transient tunnel hiccup — but in the hard wedge mode
    # (device executes nothing) every attempt burns a full deadline, and
    # run_with_deadline's contract says a timed-out process is tainted.
    # Two attempts bound time-to-error at ~2*deadline while still covering
    # the transient case.
    delays = [20.0, 60.0]
    for i in range(attempts):
        try:
            run_with_deadline(probe, deadline_s, what="compute preflight")
            return
        except DeadlineExceeded as e:
            # only the hang is worth retrying; anything else (OOM, bad
            # flag, config error) is deterministic — propagate it intact
            msg = (
                f"compute preflight timed out after {deadline_s:.0f}s — "
                "device executes nothing though backend init succeeded "
                "(wedged grant/tunnel; a later retry may succeed)"
            )
            _progress(f"preflight attempt {i + 1}/{attempts}: {msg}")
            if i == attempts - 1:
                raise RuntimeError(msg) from e
            time.sleep(delays[min(i, len(delays) - 1)])


def _is_oom(e: Exception) -> bool:
    s = f"{type(e).__name__}: {e}".lower()
    return "resource_exhausted" in s or "out of memory" in s or "oom" in s


def _bench_cost_model(n_dev: int, platform: str):
    """Committed calibration profile for this platform when one exists
    (tpu_v5e_family on chip, cpu_family on the virtual mesh; override with
    MGWFBP_BENCH_PROFILE), else the warned uncalibrated prior."""
    from mgwfbp_tpu.parallel.costmodel import committed_profile_or_prior

    default = (
        "cpu_family.json" if platform == "cpu" else "tpu_v5e_family.json"
    )
    path = os.environ.get(
        "MGWFBP_BENCH_PROFILE",
        os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "profiles", default
        ),
    )
    return committed_profile_or_prior(path, "ici", max(n_dev, 2))


def _bench_policy(
    policy, make_state, model, meta, tx, mesh, batch_dict, tb, iters,
    compute_dtype=None, cost_model=None,
):
    """Build the step for one policy, warm up, time with windowed host sync.

    Returns (sec_per_iter, merge_groups, flops_per_step)."""
    import jax

    from mgwfbp_tpu.parallel.allreduce import make_merged_allreduce
    from mgwfbp_tpu.parallel.costmodel import lookup_alpha_beta
    from mgwfbp_tpu.parallel.mesh import DATA_AXIS
    from mgwfbp_tpu.train import make_train_step

    n_dev = mesh.devices.size
    state = make_state()  # fresh per policy: buffers are DONATED below
    if policy == "none":
        reducer = None  # XLA-fused oracle (reference ORIGINAL_HOROVOD)
    else:
        reducer = make_merged_allreduce(
            state.params,
            axis_name=DATA_AXIS,
            policy=policy,
            tb=tb if policy in ("mgwfbp", "auto") else None,
            cost_model=(
                cost_model
                if cost_model is not None
                else lookup_alpha_beta("ici", max(n_dev, 2))
            ),
            comm_op=os.environ.get("MGWFBP_BENCH_COMM_OP", "all_reduce"),
        )
    # donate=True: the state buffers are reused in place across steps —
    # the production configuration (and ~4% faster than copying)
    step = make_train_step(
        model, meta, tx, mesh, reducer, compute_dtype=compute_dtype,
        donate=True,
    )

    # AOT-compile ONCE: the same executable serves cost analysis and the
    # timed loop (lowering twice would double bench startup on real TPU)
    flops = None
    run = step
    try:
        compiled = step.lower(state, batch_dict).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0)) or None
        run = compiled
    except Exception:
        flops = None
    # warmup, synchronized by a host scalar pull
    for _ in range(5):
        state, metrics = run(state, batch_dict)
    float(metrics["loss"])

    # Sync discipline: every step chains through `state` (donated), so the
    # device executes steps strictly in order and pulling a scalar computed
    # by step i forces steps 1..i to have run. ONE pull after the last step
    # therefore brackets the whole timed region exactly. Each extra pull
    # costs a full host<->device round trip — measured at ~50 ms through
    # this chip's network tunnel (per-step pulls: 139 ms/step vs 53 ms at
    # end-only sync for the same program) — so intermediate pulls would
    # time the tunnel, not the device. MGWFBP_BENCH_SYNC=iter|window
    # restores per-step / per-10-step pulls for A/B-ing the harness.
    sync_mode = os.environ.get("MGWFBP_BENCH_SYNC", "end")
    windows = {"iter": 1, "window": 10, "end": iters}
    if sync_mode not in windows:
        raise ValueError(
            f"MGWFBP_BENCH_SYNC={sync_mode!r}: expected one of "
            f"{sorted(windows)}"
        )
    window = windows[sync_mode]
    loss = None
    t0 = time.perf_counter()
    for i in range(iters):
        state, metrics = run(state, batch_dict)
        if (i + 1) % window == 0 or i == iters - 1:
            loss = float(metrics["loss"])
    dt = (time.perf_counter() - t0) / iters
    del state
    if not (loss == loss):  # NaN guard: timing a diverged program is moot
        raise RuntimeError(f"policy {policy}: non-finite loss in timed loop")
    groups = reducer.schedule.num_groups if reducer is not None else 0
    return dt, groups, flops, reducer


def run_bench() -> dict:
    from mgwfbp_tpu.utils.platform import apply_platform_overrides

    apply_platform_overrides()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mgwfbp_tpu import models as zoo
    from mgwfbp_tpu.config import PRESETS
    from mgwfbp_tpu.optim import make_optimizer
    from mgwfbp_tpu.parallel.allreduce import arrival_order
    from mgwfbp_tpu.parallel.mesh import MeshSpec, make_mesh
    from mgwfbp_tpu.profiling import benchmark_trainer_backward
    from mgwfbp_tpu.train import create_train_state

    model_name = os.environ.get("MGWFBP_BENCH_MODEL", "resnet50")
    preset_bs = PRESETS.get(model_name, {}).get("batch_size", 32)
    batch = int(os.environ.get("MGWFBP_BENCH_BATCH", str(preset_bs)))
    iters = int(os.environ.get("MGWFBP_BENCH_ITERS", "50"))
    # bf16 compute is the native TPU path (master weights stay fp32, the
    # reference's apex-O2 analogue); MGWFBP_BENCH_DTYPE=float32 opts out
    dtype_name = os.environ.get("MGWFBP_BENCH_DTYPE", "bfloat16")
    import jax.numpy as _jnp

    compute_dtype = (
        None if dtype_name in ("float32", "f32") else _jnp.dtype(dtype_name)
    )

    devices = _devices_with_retry()
    _progress(f"backend up: {devices}")
    _compute_preflight()
    _progress("compute preflight ok")
    n_dev = len(devices)
    cost_model, cost_src = _bench_cost_model(n_dev, devices[0].platform)
    mesh = make_mesh(MeshSpec(data=n_dev))
    model, meta = zoo.create_model(model_name)
    tx, _ = make_optimizer(
        0.01, momentum=0.9, weight_decay=1e-4, lr_schedule="const",
        dataset="imagenet", num_batches_per_epoch=1,
    )
    def make_state():
        return create_train_state(
            jax.random.PRNGKey(0), model,
            jnp.zeros((1,) + tuple(meta.input_shape), meta.input_dtype), tx,
        )

    state = make_state()  # for the tb measurement only

    def make_batch(per_dev):
        rs = np.random.RandomState(0)
        gb = per_dev * n_dev
        shape = (1, gb) + tuple(meta.input_shape)
        return gb, {
            "x": jnp.asarray(rs.randn(*shape)).astype(meta.input_dtype),
            "y": jnp.asarray(
                rs.randint(0, meta.num_classes, (1, gb)), jnp.int32
            ),
        }

    def run_grid(per_dev):
        """tb measurement + full policy grid at ONE batch size — the A/B
        grid must never mix batch sizes, and the mgwfbp schedule must come
        from a tb profile measured at the batch it is timed at."""
        _progress(f"materializing batch (per-device {per_dev})")
        gb, bd = make_batch(per_dev)
        paths = jax.tree_util.tree_flatten_with_path(state.params)[0]
        names = [jax.tree_util.keystr(kp) for kp, _ in paths]
        perm = arrival_order(len(names), names=names)
        micro = {"x": bd["x"][0, :per_dev], "y": bd["y"][0, :per_dev]}
        # measured tb: real backward wall clock (scale measured, not
        # invented — VERDICT r2 Weak #4); trace-attributed when possible
        _progress(f"tb backward profiling (batch {per_dev})")
        tb_prof = benchmark_trainer_backward(
            model, meta, state.params, state.batch_stats, micro, perm,
            warmup=2, iters=5, names=names, compute_dtype=compute_dtype,
        )
        grid: dict[str, dict] = {}
        reducers: dict[str, object] = {}
        for policy in _POLICIES:
            _progress(f"policy {policy}: build + compile + time")
            dt, groups, flops, reducer = _bench_policy(
                policy, make_state, model, meta, tx, mesh, bd, tb_prof,
                iters, compute_dtype=compute_dtype, cost_model=cost_model,
            )
            grid[policy] = {
                "sec_per_iter": round(dt, 6),
                "images_per_sec": round(gb / dt, 2),
                "merge_groups": groups,
                "flops_per_step": flops,
            }
            reducers[policy] = reducer
        return gb, tb_prof, grid, reducers

    batch_fallback = False
    try:
        global_batch, tb, results, reducers = run_grid(batch)
    except Exception as e:
        if not (_is_oom(e) and batch > 64):
            raise
        # preset batch doesn't fit this chip: rerun the ENTIRE grid at 64
        batch_fallback = True
        batch = 64
        global_batch, tb, results, reducers = run_grid(batch)

    # Headline = the PRODUCTION configuration. On one device the Trainer
    # skips the reducer entirely (reference single-path parity:
    # train_with_single never wraps the optimizer), which is exactly the
    # 'none' row; the instrumented mgwfbp row stays in `policies` so the
    # no-op-dispatch overhead remains visible. Multi-device headline is
    # `auto` — the production default policy (config.py) — matching the
    # reference's ADAPTIVE_MERGE-on default.
    headline_policy = "none" if n_dev == 1 else "auto"
    main = results[headline_policy]
    dt = main["sec_per_iter"]
    img_s = main["images_per_sec"]
    flops = main["flops_per_step"]
    peak = _peak_flops(devices[0].device_kind)
    mfu = None
    if flops and peak:
        mfu = flops / dt / (peak * n_dev)

    payload = {
        "metric": f"{model_name}_synthetic_{meta.dataset}_train_throughput",
        "value": img_s,
        "unit": "images/s",
        "vs_baseline": round(img_s / P100_RESNET50_IMG_S, 3),
        # the row the headline numbers actually come from; the single-device
        # production rationale lives in "note"
        "policy": headline_policy,
        "n_devices": n_dev,
        "device_kind": devices[0].device_kind,
        "batch_per_device": batch,
        "batch_fallback": batch_fallback,
        "compute_dtype": dtype_name,
        "iters": iters,
        "sec_per_iter": dt,
        "merge_groups": main["merge_groups"],
        "policies": {
            k: {kk: vv for kk, vv in v.items() if kk != "flops_per_step"}
            for k, v in results.items()
        },
        "tb_total_s": round(sum(tb), 6),
        "cost_profile": cost_src or "UNCALIBRATED ici prior",
    }
    if mfu is not None:
        payload["mfu"] = round(mfu, 4)
    if flops is not None:
        payload["flops_per_step"] = flops
    headline_reducer = reducers.get(headline_policy)
    if headline_reducer is not None:
        # overlap-efficiency summary for the headline configuration (the
        # paper's hidden-vs-exposed comm accounting, telemetry/overlap.py)
        # — cost-model-attributed here: the bench loop is not traced
        from mgwfbp_tpu.telemetry import summarize as overlap_summarize

        s = overlap_summarize(headline_reducer, cost_model, list(tb), dt)
        payload["overlap"] = {
            "comm_s": round(s.comm_s, 6),
            "hidden_s": round(s.hidden_s, 6),
            "exposed_s": round(s.exposed_s, 6),
            "efficiency": round(s.efficiency, 4),
            "attribution": s.attribution,
        }
    if n_dev == 1:
        payload["note"] = (
            "single chip: headline is the PRODUCTION configuration — the "
            "Trainer skips the reducer at world size 1 (reference "
            "single-path parity), i.e. the 'none' row. Collectives are "
            "no-ops here, so the XLA-fused oracle "
            "('none'/'single') is the ceiling and merge scheduling can only "
            "add dispatch overhead; MG-WFBP's advantage needs real "
            "inter-chip communication (compare policies on a multi-chip "
            "mesh)."
        )
    if mfu is not None and mfu > 1.0:
        # physically impossible: the measurement layer is broken; refuse to
        # report a throughput number (VERDICT r2 Weak #2)
        payload.update(
            {
                "value": None,
                "vs_baseline": None,
                "error": (
                    f"computed MFU {mfu:.3f} > 1.0 — timing not credible "
                    f"(dt={dt}, flops={flops}, peak={peak})"
                ),
            }
        )
    return payload


def _record_bench_skip(detail: str) -> None:
    """Append a structured bench_skip record to the telemetry stream at
    MGWFBP_TELEMETRY_DIR (when set) — the same typed event family live
    runs write, so outage post-mortems grep one format."""
    d = os.environ.get("MGWFBP_TELEMETRY_DIR")
    if not d:
        return
    try:
        from mgwfbp_tpu.telemetry import EventWriter

        w = EventWriter(
            os.path.join(d, "telemetry.jsonl"), run={"source": "bench"}
        )
        w.emit("bench_skip", detail=detail)
        w.close()
    except Exception:  # noqa: BLE001 — observability must not turn a
        # structured skip (rc=0) into a crash (rc=1)
        pass


def main() -> int:
    try:
        payload = run_bench()
        _emit(payload)
        return 1 if payload.get("error") else 0
    except ChipUnavailable as e:
        # structured skip, exit 0: the trajectory reads "no chip this
        # round", not "regression" — a null metric with rc=1 is
        # indistinguishable from real breakage (BENCH_r01..r05)
        _record_bench_skip(f"{type(e).__name__}: {e}")
        _emit(
            {
                "metric": "resnet50_synthetic_imagenet_train_throughput",
                "value": None,
                "unit": "images/s",
                "vs_baseline": None,
                "skipped": "chip unavailable",
                "detail": f"{type(e).__name__}: {e}",
            }
        )
        return 0
    except Exception as e:  # noqa: BLE001 — one JSON line, never a traceback
        _emit(
            {
                "metric": "resnet50_synthetic_imagenet_train_throughput",
                "value": None,
                "unit": "images/s",
                "vs_baseline": None,
                "error": f"{type(e).__name__}: {e}",
            }
        )
        return 1


if __name__ == "__main__":
    sys.exit(main())
